"""Watch-backed Argo engine: cache, event-driven wake, degradation.

The informer divergence (docs/design.md): one WATCH per namespace
replaces per-workflow polling GETs, and the reconciler's poll loop
wakes on the workflow's terminal event instead of sleeping out its
inverse-exp delay.
"""

import asyncio

import pytest

from activemonitor_tpu.engine.argo import (
    WF_GROUP,
    WF_PLURAL,
    WF_VERSION,
    ArgoWorkflowEngine,
)
from activemonitor_tpu.kube import api_path

from tests.kube_harness import stub_env

from activemonitor_tpu.engine.base import WF_INSTANCE_ID, WF_INSTANCE_ID_LABEL_KEY

# carries the instance-id label like every spec the workflow mutator
# renders — the watch cache is scoped to it
MANIFEST = {
    "apiVersion": "argoproj.io/v1alpha1",
    "kind": "Workflow",
    "metadata": {
        "generateName": "probe-",
        "namespace": "health",
        "labels": {WF_INSTANCE_ID_LABEL_KEY: WF_INSTANCE_ID},
    },
    "spec": {"entrypoint": "main"},
}


async def _warm_watch(engine, namespace="health"):
    watch = engine._watches[namespace]
    for _ in range(100):
        if watch.healthy:
            return watch
        await asyncio.sleep(0.02)
    raise TimeoutError("watch never became healthy")


@pytest.mark.asyncio
async def test_get_served_from_cache_without_apiserver_roundtrip():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        try:
            name = await eng.submit(dict(MANIFEST))
            await _warm_watch(eng)
            # any direct GET of the object would consume this fault; a
            # cache hit never touches the server
            server.inject_fault(f"/workflows/{name}", status=500, method="GET")
            wf = await eng.get("health", name)
            assert wf["metadata"]["name"] == name
            assert server.faults[0]["remaining"] == 1  # untouched
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_cache_tracks_status_patches():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        try:
            name = await eng.submit(dict(MANIFEST))
            watch = await _warm_watch(eng)
            await api.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", name, "status"),
                {"status": {"phase": "Succeeded"}},
            )
            for _ in range(100):
                cached = watch.lookup(name)
                if (cached.get("status") or {}).get("phase") == "Succeeded":
                    break
                await asyncio.sleep(0.02)
            else:
                raise AssertionError("cache never saw the status patch")
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_wait_change_wakes_on_patch():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        try:
            name = await eng.submit(dict(MANIFEST))
            await _warm_watch(eng)
            waiter = asyncio.create_task(eng.wait_change("health", name))
            await asyncio.sleep(0.05)
            assert not waiter.done()  # no change yet: blocked
            await api.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", name, "status"),
                {"status": {"phase": "Succeeded"}},
            )
            await asyncio.wait_for(waiter, timeout=5.0)  # event-driven wake
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_watch_survives_stream_drop():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        try:
            name = await eng.submit(dict(MANIFEST))
            await _warm_watch(eng)
            assert server.drop_watches() >= 1
            await asyncio.sleep(0.1)
            await api.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", name, "status"),
                {"status": {"phase": "Failed"}},
            )
            # reconnected watch (or GET fallback) must converge
            for _ in range(200):
                wf = await eng.get("health", name)
                if (wf.get("status") or {}).get("phase") == "Failed":
                    break
                await asyncio.sleep(0.02)
            else:
                raise AssertionError("engine never converged after stream drop")
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_cache_miss_falls_back_to_direct_get():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        try:
            await eng.submit(dict(MANIFEST))
            await _warm_watch(eng)
            # created behind the cache's back is impossible (events cover
            # it) — but a never-existing name must come back None via the
            # direct GET, not a false cache verdict
            assert await eng.get("health", "ghost") is None
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_watch_health_callback_and_gauge():
    from activemonitor_tpu.metrics import MetricsCollector

    collector = MetricsCollector()
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api, on_watch_health=collector.record_watch_health)
        try:
            await eng.submit(dict(MANIFEST))
            await _warm_watch(eng)
            healthy = collector.workflow_watch_healthy.labels("health")
            assert healthy._value.get() == 1.0
        finally:
            await eng.close()
    # api closed under the watch: next reconnect attempt flips unhealthy
    # (the engine was closed first, so just assert the gauge exists and
    # the callback path wired — the flip is covered by _set_healthy's
    # transition guard below)
    collector.record_watch_health("health", False)
    assert collector.workflow_watch_healthy.labels("health")._value.get() == 0.0


@pytest.mark.asyncio
async def test_watch_health_gauge_seeded_when_unhealthy_from_start():
    from activemonitor_tpu.engine.argo import _NamespaceWatch
    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.metrics import MetricsCollector

    collector = MetricsCollector()
    # an apiserver that refuses connections: the watch never becomes
    # healthy, but the 0 series must exist from the first attempt
    api = KubeApi(KubeConfig(server="http://127.0.0.1:1"))
    watch = _NamespaceWatch(api, "health", on_health=collector.record_watch_health)
    watch.ensure_started()
    try:
        await asyncio.sleep(0.2)
        assert (
            collector.workflow_watch_healthy.labels("health")._value.get() == 0.0
        )
    finally:
        await watch.stop()
        await api.close()


@pytest.mark.asyncio
async def test_never_connecting_watch_reports_unhealthy_and_counts_restarts():
    """A namespace watch that never connects (connection refused at
    startup) must surface through BOTH wired callbacks — the health
    gauge reads 0 and workflow_watch_restarts_total counts every
    re-establishment attempt — instead of staying silently at its
    initial state."""
    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.metrics import MetricsCollector

    collector = MetricsCollector()
    api = KubeApi(KubeConfig(server="http://127.0.0.1:1"))
    eng = ArgoWorkflowEngine(
        api,
        on_watch_health=collector.record_watch_health,
        on_watch_restart=collector.record_watch_restart,
    )
    try:
        # a read starts the namespace watch; the direct-GET fallback
        # fails too (the server is down) — that error is the caller's
        with pytest.raises(Exception):
            await eng.get("health", "ghost")
        deadline = asyncio.get_event_loop().time() + 10.0
        while True:
            restarts = collector.sample_value(
                "workflow_watch_restarts_total", {"namespace": "health"}
            )
            if restarts and restarts >= 1:
                break
            assert asyncio.get_event_loop().time() < deadline, (
                "watch restarts never counted for a never-connecting watch"
            )
            await asyncio.sleep(0.05)
        assert (
            collector.sample_value(
                "workflow_watch_healthy", {"namespace": "health"}
            )
            == 0.0
        )
    finally:
        await eng.close()
        await api.close()


@pytest.mark.asyncio
async def test_dead_watch_task_flips_unhealthy_and_restart_is_counted():
    """A watch task that dies outright (not via stop()) must not leave
    the cache advertising its last healthy state — and reviving it
    counts as a stream restart."""
    from activemonitor_tpu.metrics import MetricsCollector

    collector = MetricsCollector()
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(
            api,
            on_watch_health=collector.record_watch_health,
            on_watch_restart=collector.record_watch_restart,
        )
        try:
            name = await eng.submit(dict(MANIFEST))
            watch = await _warm_watch(eng)
            assert (
                collector.sample_value(
                    "workflow_watch_healthy", {"namespace": "health"}
                )
                == 1.0
            )
            # kill the task from outside (a bug escaping the retry
            # ladder looks the same): health must flip to 0
            watch._task.cancel()
            for _ in range(100):
                if not watch.healthy:
                    break
                await asyncio.sleep(0.02)
            assert not watch.healthy
            assert (
                collector.sample_value(
                    "workflow_watch_healthy", {"namespace": "health"}
                )
                == 0.0
            )
            restarts_before = (
                collector.sample_value(
                    "workflow_watch_restarts_total", {"namespace": "health"}
                )
                or 0.0
            )
            # the next engine call revives the watch, counting a restart
            await eng.get("health", name)
            assert (
                collector.sample_value(
                    "workflow_watch_restarts_total", {"namespace": "health"}
                )
                == restarts_before + 1
            )
            await _warm_watch(eng)  # and it becomes healthy again
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_closed_engine_does_not_resurrect_watches():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        name = await eng.submit(dict(MANIFEST))
        await _warm_watch(eng)
        await eng.close()
        # a straggler get() after close must not spawn a new watch task
        watch = eng._watches["health"]
        task_after_close = watch._task
        await eng.get("health", name)
        assert watch._task is task_after_close
        assert task_after_close.done()


@pytest.mark.asyncio
async def test_cache_scoped_to_instance_id_label():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        try:
            await eng.submit(dict(MANIFEST))
            watch = await _warm_watch(eng)
            # a foreign workflow in the same namespace (no instance-id
            # label) must never be mirrored into controller memory
            foreign = {
                "apiVersion": "argoproj.io/v1alpha1",
                "kind": "Workflow",
                "metadata": {"name": "foreign-wf", "namespace": "health"},
                "spec": {},
            }
            server.seed(WF_GROUP, WF_VERSION, WF_PLURAL, foreign)
            await asyncio.sleep(0.2)
            assert watch.lookup("foreign-wf") is None
            # ...but a direct get still reaches it (fallback path)
            wf = await eng.get("health", "foreign-wf")
            assert wf["metadata"]["name"] == "foreign-wf"
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_get_fresh_bypasses_stale_cache():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        try:
            name = await eng.submit(dict(MANIFEST))
            watch = await _warm_watch(eng)
            await api.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", name, "status"),
                {"status": {"phase": "Succeeded"}},
            )
            # simulate a lagging cache (watch reconnect gap): the entry
            # still says Running while the server says Succeeded —
            # get() serves the stale hit, the timed-out final poll's
            # get_fresh() must see the server's truth
            watch._cache[name] = {
                "metadata": {"name": name, "resourceVersion": "0"},
                "status": {"phase": "Running"},
            }
            stale = await eng.get("health", name)
            assert (stale.get("status") or {}).get("phase") == "Running"
            fresh = await eng.get_fresh("health", name)
            assert (fresh.get("status") or {}).get("phase") == "Succeeded"
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_watch_disabled_engine_never_watches():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api, watch=False)
        try:
            name = await eng.submit(dict(MANIFEST))
            assert eng._watches == {}
            wf = await eng.get("health", name)  # plain GET path
            assert wf["metadata"]["name"] == name
        finally:
            await eng.close()


@pytest.mark.asyncio
async def test_reconciler_completes_event_driven():
    """The latency win end-to-end: workflow timeout 120s means the first
    poll delay is 60s — the check still completes in seconds because the
    status patch wakes the loop through the watch."""
    from activemonitor_tpu.api import HealthCheck
    from activemonitor_tpu.controller import RBACProvisioner
    from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient
    from activemonitor_tpu.controller.events import KubernetesEventRecorder
    from activemonitor_tpu.controller.rbac import KubernetesRBACBackend
    from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
    from activemonitor_tpu.metrics import MetricsCollector

    check = HealthCheck.from_dict(
        {
            "metadata": {"name": "fast-detect", "namespace": "health"},
            "spec": {
                "repeatAfterSec": 600,
                "level": "namespace",
                "workflow": {
                    "generateName": "fast-",
                    "workflowtimeout": 120,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "fast-sa",
                        "source": {
                            "inline": (
                                "apiVersion: argoproj.io/v1alpha1\n"
                                "kind: Workflow\n"
                                "metadata:\n  generateName: fast-\n"
                                "spec:\n  entrypoint: main\n"
                            )
                        },
                    },
                },
            },
        }
    )
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        eng = ArgoWorkflowEngine(api)
        reconciler = HealthCheckReconciler(
            client=client,
            engine=eng,
            rbac=RBACProvisioner(KubernetesRBACBackend(api)),
            recorder=KubernetesEventRecorder(api),
            metrics=MetricsCollector(),
        )
        try:
            await client.apply(check)
            await reconciler.reconcile("health", "fast-detect")
            for _ in range(100):
                wfs = server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)
                if wfs:
                    break
                await asyncio.sleep(0.05)
            name = wfs[0]["metadata"]["name"]
            await api.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", name, "status"),
                {"status": {"phase": "Succeeded"}},
            )

            async def succeeded():
                hc = await client.get("health", "fast-detect")
                return hc is not None and hc.status.status == "Succeeded"

            deadline = asyncio.get_event_loop().time() + 10.0
            while not await succeeded():
                assert (
                    asyncio.get_event_loop().time() < deadline
                ), "event-driven wake missed: loop slept out its 60s delay"
                await asyncio.sleep(0.05)
            hc = await client.get("health", "fast-detect")
            assert hc.status.success_count == 1
        finally:
            await reconciler.shutdown()
            await eng.close()
