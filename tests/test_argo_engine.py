"""Argo engine tests against the in-process stub API server.

The engine creates/polls real Workflow CRs over REST — the stub plays
the API server with the Workflow CRD installed, exactly the reference's
envtest trick (SURVEY.md §4: the CRD itself is the fake backend).
"""

import asyncio

import pytest

from activemonitor_tpu.engine.argo import WF_GROUP, WF_PLURAL, WF_VERSION, ArgoWorkflowEngine
from activemonitor_tpu.kube import ApiError

from tests.kube_harness import stub_env

MANIFEST = {
    "apiVersion": "argoproj.io/v1alpha1",
    "kind": "Workflow",
    "metadata": {"generateName": "probe-", "namespace": "health"},
    "spec": {"entrypoint": "main"},
}


@pytest.mark.asyncio
async def test_submit_returns_generated_name():
    async with stub_env() as (server, api):
        eng = ArgoWorkflowEngine(api)
        name = await eng.submit(dict(MANIFEST))
        assert name.startswith("probe-")
        assert server.obj(WF_GROUP, WF_VERSION, WF_PLURAL, "health", name) is not None


@pytest.mark.asyncio
async def test_get_found_and_not_found():
    async with stub_env() as (_, api):
        eng = ArgoWorkflowEngine(api)
        name = await eng.submit(dict(MANIFEST))
        wf = await eng.get("health", name)
        assert wf["metadata"]["name"] == name
        assert await eng.get("health", "ghost") is None  # 404 -> None


@pytest.mark.asyncio
async def test_get_other_errors_propagate():
    async with stub_env(token="sekret") as (server, _):
        from activemonitor_tpu.kube import KubeApi, KubeConfig

        unauthed = KubeApi(KubeConfig(server=server.url))  # 401s
        try:
            eng = ArgoWorkflowEngine(unauthed)
            with pytest.raises(ApiError):
                await eng.get("health", "x")
        finally:
            await unauthed.close()


@pytest.mark.asyncio
async def test_reconciler_works_through_argo_engine():
    """Full reconcile loop over the stub API server: submit, poll,
    scripted completion, status + reschedule."""
    from activemonitor_tpu.api import HealthCheck
    from activemonitor_tpu.controller import (
        EventRecorder,
        HealthCheckReconciler,
        InMemoryHealthCheckClient,
        InMemoryRBACBackend,
        RBACProvisioner,
    )
    from activemonitor_tpu.metrics import MetricsCollector
    from activemonitor_tpu.utils.clock import FakeClock

    async with stub_env() as (server, api):
        client = InMemoryHealthCheckClient()
        clock = FakeClock()
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(InMemoryRBACBackend()),
            recorder=EventRecorder(),
            metrics=MetricsCollector(),
            clock=clock,
        )
        hc = HealthCheck.from_dict(
            {
                "metadata": {"name": "argo-hc", "namespace": "health"},
                "spec": {
                    "repeatAfterSec": 60,
                    "level": "cluster",
                    "workflow": {
                        "generateName": "argo-hc-",
                        "workflowtimeout": 10,
                        "resource": {
                            "namespace": "health",
                            "serviceAccount": "sa",
                            "source": {
                                "inline": "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"
                            },
                        },
                    },
                },
            }
        )
        created = await client.apply(hc)
        await reconciler.reconcile(created.namespace, created.name)
        # deterministic wait: poll for the submitted workflow with a
        # deadline instead of a fixed sleep (CI machines vary)
        deadline = asyncio.get_event_loop().time() + 10
        while not server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
            assert asyncio.get_event_loop().time() < deadline, "no workflow submitted"
            await asyncio.sleep(0.02)
        # the Argo controller "completes" the workflow
        wfs = server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)
        assert len(wfs) == 1
        wfs[0]["status"] = {"phase": "Succeeded"}
        await clock.advance(10)  # next poll observes the terminal phase
        await reconciler.wait_watches()
        st = (await client.get("health", "argo-hc")).status
        assert st.status == "Succeeded"
        assert st.success_count == 1
