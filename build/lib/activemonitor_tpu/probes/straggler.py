"""Straggler probe — find the one sick chip in a slice.

Collective benchmarks (ici/collectives probes) measure the WHOLE mesh:
one degraded chip drags every collective down but does not say which
chip. This probe runs an identical single-chip matmul chain on every
device independently — no collectives, so a slow chip cannot hide
behind its neighbors — and compares:

1. timing spread — worst device time over the median; a healthy slice
   sits within a few percent, a throttled/sick chip sticks out;
2. numeric agreement — all devices run the same computation on the
   same inputs, so results must match bitwise on identical silicon; a
   mismatch is the scariest failure (silent data corruption).

SPMD collectives stall at the speed of the slowest participant, so the
spread here is a direct forecast of the whole slice's training-step
time. Complements the per-axis collective sweep (which localizes a
torus DIRECTION); this localizes a CHIP.

Single-device runs degrade to an informational pass (nothing to
compare), mirroring the multi-chip probes.
"""

from __future__ import annotations

import hashlib
import statistics

import jax
import jax.numpy as jnp
import numpy as np

from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.utils.timing import chain_delta_seconds


def _device_measure(device, dim: int, iters: int) -> tuple:
    """(seconds-per-matmul, chain checksum) on one device.

    Inputs are committed to the device, so the jitted chain executes
    there; the chain-delta discipline cancels dispatch/tunnel overhead
    the same way it does for the aggregate probes."""
    a = jax.device_put(
        jax.random.normal(jax.random.key(0), (dim, dim), jnp.bfloat16), device
    )
    b = jax.device_put(
        jax.random.normal(jax.random.key(1), (dim, dim), jnp.bfloat16), device
    )

    def make_chain(k):
        @jax.jit
        def chain(a, x):
            for _ in range(k):  # data-dependent: each feeds the next
                x = jnp.dot(a, x, preferred_element_type=jnp.bfloat16)
            return x.astype(jnp.float32).sum()

        return chain

    seconds = chain_delta_seconds(make_chain, a, b, k1=2, k2=8, iters=iters)

    @jax.jit
    def chain_full(a, x):
        for _ in range(4):
            x = jnp.dot(a, x, preferred_element_type=jnp.bfloat16)
        return x

    # digest of the raw result bytes — a scalar-sum checksum would let
    # single-lane corruption vanish into the accumulator's rounding
    digest = hashlib.sha256(
        np.ascontiguousarray(np.asarray(chain_full(a, b))).tobytes()
    ).hexdigest()
    return seconds, digest


def run(
    dim: int = 0,
    iters: int = 5,
    threshold: float = 1.25,
) -> ProbeResult:
    """``threshold`` is the worst/median timing ratio above which a
    device is flagged (collectives run at the slowest chip's pace, so
    1.25 means ~25 % of the whole slice's throughput is being lost)."""
    # local devices only: on multi-host slices most of jax.devices() is
    # non-addressable from this process and device_put would raise —
    # each host measures its own chips (run the probe once per host to
    # cover a pod; the battery runs host-local by construction)
    devices = jax.local_devices()
    on_tpu = devices[0].platform == "tpu"
    if dim <= 0:
        dim = 2048 if on_tpu else 256

    per_device = {}
    checksums = {}
    for device in devices:
        seconds, checksum = _device_measure(device, dim, iters)
        per_device[device.id] = seconds
        checksums[device.id] = checksum

    median = statistics.median(per_device.values())
    worst_id, worst = max(per_device.items(), key=lambda kv: kv[1])
    spread = worst / median if median > 0 else 1.0
    slow = sorted(
        d for d, s in per_device.items() if median > 0 and s / median > threshold
    )
    distinct_checksums = len(set(checksums.values()))
    numerics_agree = distinct_checksums == 1

    metrics = [
        ProbeMetric(
            "straggler-worst-over-median",
            spread,
            help="Slowest device's per-op time / median across devices",
        ),
        ProbeMetric(
            "straggler-slow-devices",
            float(len(slow)),
            help="Devices slower than threshold x median",
        ),
        ProbeMetric(
            "straggler-numeric-agreement",
            1.0 if numerics_agree else 0.0,
            help="1 if every device produced a bitwise-identical result",
        ),
    ]
    details = {
        "devices": len(devices),
        "hosts": jax.process_count(),
        "host_local": jax.process_count() > 1,
        "dim": dim,
        "per_device_ms": {d: round(s * 1e3, 3) for d, s in per_device.items()},
        "median_ms": round(median * 1e3, 3),
        "worst_device": worst_id,
        "spread": round(spread, 3),
        "slow_devices": slow,
        "distinct_checksums": distinct_checksums,
    }
    if len(devices) < 2:
        # nothing to compare against — informational pass
        return ProbeResult(
            ok=True,
            summary=(
                f"single device: {per_device[worst_id]*1e3:.2f} ms/op "
                "(no straggler comparison possible)"
            ),
            metrics=metrics,
            details=details,
        )
    # timing spread only gates on real TPU: virtual/CPU "devices" share
    # host cores, so their spread is scheduler noise, not silicon health
    ok = numerics_agree and (not slow or not on_tpu)
    if not numerics_agree:
        verdict = f"NUMERIC MISMATCH across devices ({distinct_checksums} distinct results)"
    elif slow:
        verdict = f"stragglers: devices {slow} at >{threshold:.2f}x median" + (
            "" if on_tpu else " (informational off-TPU)"
        )
    else:
        verdict = "no stragglers"
    summary = (
        f"{len(devices)} devices, spread {spread:.2f}x "
        f"(worst: device {worst_id}) — {verdict}"
    )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
