"""Inline artifact reader (reference: internal/store/inline.go:10-26)."""

from __future__ import annotations


class InlineReader:
    """Serves a manifest embedded directly in the HealthCheck spec."""

    def __init__(self, inline: str):
        if not inline:
            raise ValueError("InlineArtifact does not exist")
        self._inline = inline

    def read(self) -> bytes:
        return self._inline.encode("utf-8")
