"""Kubernetes-native scrape authn/z — TokenReview + SubjectAccessReview.

The reference guards /metrics with controller-runtime's
``WithAuthenticationAndAuthorization`` filter
(/root/reference/cmd/main.go:74-81): every scrape's bearer token is
validated by the API server (TokenReview) and the resulting identity
is authorized for the endpoint (SubjectAccessReview on the
non-resource URL). This module is that filter for the aiohttp metrics
endpoint: the cluster decides who may scrape, per identity, with RBAC
— no shared static secret to rotate.

Decisions are cached per token for a short TTL (the filter would
otherwise issue two API-server round trips per scrape; controller-
runtime caches the same way). Infra failures return ``None`` so the
caller can apply its fallback policy (static token if configured,
else fail closed) — an API-server blip must not silently open the
endpoint.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from activemonitor_tpu.kube.client import KubeApi

TOKENREVIEW_PATH = "/apis/authentication.k8s.io/v1/tokenreviews"
SAR_PATH = "/apis/authorization.k8s.io/v1/subjectaccessreviews"


class KubeScrapeAuthorizer:
    """allowed(token) -> True | False | None (infra failure)."""

    def __init__(
        self,
        api: KubeApi,
        path: str = "/metrics",
        verb: str = "get",
        cache_ttl: float = 60.0,
        monotonic=time.monotonic,
    ):
        self._api = api
        self._path = path
        self._verb = verb
        self._ttl = cache_ttl
        self._monotonic = monotonic
        # token -> (expiry, verdict); only definitive verdicts cached
        self._cache: Dict[str, Tuple[float, bool]] = {}

    async def allowed(self, token: str) -> Optional[bool]:
        if not token:
            return False
        now = self._monotonic()
        hit = self._cache.get(token)
        if hit is not None and hit[0] > now:
            return hit[1]

        try:
            review = await self._api.create(
                TOKENREVIEW_PATH,
                {
                    "apiVersion": "authentication.k8s.io/v1",
                    "kind": "TokenReview",
                    "spec": {"token": token},
                },
            )
        except Exception:
            # includes 401/403 on OUR credentials (a setup problem —
            # missing system:auth-delegator binding — not a verdict on
            # the scraper): every failure to ASK is an infra failure,
            # never a deny
            return None
        status = review.get("status") or {}
        if not status.get("authenticated"):
            self._remember(token, False, now)
            return False
        user = status.get("user") or {}

        try:
            sar = await self._api.create(
                SAR_PATH,
                {
                    "apiVersion": "authorization.k8s.io/v1",
                    "kind": "SubjectAccessReview",
                    "spec": {
                        "user": user.get("username", ""),
                        "groups": user.get("groups") or [],
                        "uid": user.get("uid", ""),
                        "nonResourceAttributes": {
                            "path": self._path,
                            "verb": self._verb,
                        },
                    },
                },
            )
        except Exception:
            return None
        verdict = bool((sar.get("status") or {}).get("allowed"))
        self._remember(token, verdict, now)
        return verdict

    def _remember(self, token: str, verdict: bool, now: float) -> None:
        if len(self._cache) > 1024:  # bound memory under token churn
            self._cache.clear()
        self._cache[token] = (now + self._ttl, verdict)
