"""Ring attention tests — sequence parallelism on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.ops.ring_attention import reference_attention, ring_attention
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes import ring as ring_probe


@pytest.fixture(scope="module")
def mesh():
    return make_1d_mesh("sp")


def qkv(seq=64, batch=2, heads=4, head_dim=16, dtype=jnp.float32):
    keys = jax.random.split(jax.random.key(0), 3)
    return tuple(
        jax.random.normal(k, (batch, seq, heads, head_dim), dtype) for k in keys
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(mesh, causal):
    q, k, v = qkv()
    got = ring_attention(q, k, v, mesh, "sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(got - want)) < 1e-5


@pytest.mark.parametrize("causal", [True, False])
def test_flash_block_compute_matches_reference(mesh, causal):
    # the fused per-step block compute (flash_attention_partial under
    # the ring's lax.switch) must agree with both the XLA path and the
    # single-device reference
    q, k, v = qkv(seq=128)
    flash = ring_attention(q, k, v, mesh, "sp", causal=causal, use_flash=True)
    plain = ring_attention(q, k, v, mesh, "sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(flash - want))) < 1e-5
    assert float(jnp.max(jnp.abs(flash - plain))) < 1e-5


def test_probe_flash_mode(mesh):
    result = ring_probe.run(
        batch=1, seq_per_device=16, heads=2, head_dim=16, iters=2, use_flash=True
    )
    assert result.ok
    assert result.details["block_compute"] == "flash"


def test_matches_reference_bf16(mesh):
    q, k, v = qkv(dtype=jnp.bfloat16)
    got = ring_attention(q, k, v, mesh, "sp")
    want = reference_attention(q, k, v)
    assert (
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))) < 2e-2
    )


def test_jit_compatible(mesh):
    q, k, v = qkv()
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, "sp"))
    out = fn(q, k, v)
    assert out.shape == q.shape
    assert jnp.isfinite(out).all()


def test_single_query_block_first_row(mesh):
    """Causality: token 0 attends only to itself — output equals v[0]."""
    q, k, v = qkv()
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    assert jnp.allclose(out[:, 0], v[:, 0], atol=1e-5)


def test_probe_runs_and_reports(mesh):
    result = ring_probe.run(seq_per_device=16, heads=2, head_dim=8, iters=2)
    assert result.ok
    names = {m.name for m in result.metrics}
    assert names == {
        "ring-attention-max-error",
        "ring-attention-tokens-per-second",
        "ring-attention-tflops",
    }
    assert result.details["devices"] == 8
    assert result.details["seq"] == 16 * 8


def test_distributed_detection(monkeypatch):
    from activemonitor_tpu.parallel.distributed import detect_multihost_env

    monkeypatch.delenv("ACTIVEMONITOR_DISTRIBUTED", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert not detect_multihost_env()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a")
    assert not detect_multihost_env()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a,host-b")
    assert detect_multihost_env()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("ACTIVEMONITOR_DISTRIBUTED", "1")
    assert detect_multihost_env()


def test_context_parallel_forward_matches_dense(mesh):
    """The long-context model path (seq sharded + ring attention) must
    agree with the dense single-device forward."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from activemonitor_tpu.models.probe_model import (
        forward,
        forward_context_parallel,
        init_params,
        tiny_config,
    )

    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg.vocab_size)
    sharded = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
    got = forward_context_parallel(params, sharded, cfg, mesh)
    want = forward(params, tokens, cfg)
    assert jnp.max(jnp.abs(got - want)) < 3e-2  # bf16 compute tolerance
