"""Degradation-aware resilience layer (docs/resilience.md).

Three containment mechanisms behind one coordinator:

- :mod:`breaker` — the shared circuit breaker (closed/open/half-open)
  around the kube transport's mutating calls and the engines'
  submit/poll paths; open ⇒ the controller runs in *degraded mode*.
- :mod:`health` — the per-check state machine
  (healthy → flapping → quarantined) driven off terminal verdicts and
  pre-terminal errors.
- :mod:`storm` — the fleet-wide remedy token bucket (``--remedy-rate``).

Everything in this package takes an injectable clock; ``time.time()``
is banned here by the repo linter (hack/lint.py: wall-clock-in-resilience).
"""

from activemonitor_tpu.resilience.breaker import (
    BreakerOpenError,
    CircuitBreaker,
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    is_transient_error,
)
from activemonitor_tpu.resilience.coordinator import ResilienceCoordinator
from activemonitor_tpu.resilience.health import (
    CHECK_STATES,
    CheckStateTracker,
    STATE_FLAPPING,
    STATE_HEALTHY,
    STATE_QUARANTINED,
)
from activemonitor_tpu.resilience.storm import TokenBucket

__all__ = [
    "BreakerOpenError",
    "CHECK_STATES",
    "CheckStateTracker",
    "CircuitBreaker",
    "ResilienceCoordinator",
    "STATE_CLOSED",
    "STATE_FLAPPING",
    "STATE_HALF_OPEN",
    "STATE_HEALTHY",
    "STATE_OPEN",
    "STATE_QUARANTINED",
    "TokenBucket",
    "is_transient_error",
]
