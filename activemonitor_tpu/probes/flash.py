"""Flash-attention probe — fused single-chip attention health + perf.

Two verdicts in one probe (the single-chip sibling of the ring probe):

1. correctness — the Pallas fused kernel (ops/flash_attention.py) must
   match unfused reference attention; a mismatch means the Mosaic
   compile or the chip's MXU/VPU path is producing wrong numbers;
2. throughput — achieved attention TFLOP/s of the fused kernel, with
   the unfused XLA attention timed alongside as the speedup baseline.
   A fused/unfused ratio collapsing toward 1 means the kernel stopped
   being fused (toolchain regression) long before absolute numbers
   drift.

Off-TPU the kernel runs in interpret mode: correctness is still checked
(same code path) but timing falls back to the XLA expression, mirroring
the HBM probe's policy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.ops.flash_attention import attention_flops, flash_attention
from activemonitor_tpu.ops.ring_attention import reference_attention
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    batch: int = 4,
    seq: int = 4096,
    heads: int = 8,
    head_dim: int = 128,
    iters: int = 5,
    causal: bool = True,
    tolerance: float = 2e-2,
) -> ProbeResult:
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if not on_tpu and seq > 512:
        seq = 512  # interpret-mode correctness is O(minutes) beyond this
    dtype = jnp.bfloat16
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (
        jax.random.normal(kk, (batch, seq, heads, head_dim), dtype) for kk in keys
    )

    # correctness on a small slice (unfused reference materializes the
    # [S, S] scores — keep it tractable); block sizes forced small so
    # the online-softmax accumulation really iterates
    small = min(seq, 512)
    got = flash_attention(
        q[:, :small], k[:, :small], v[:, :small],
        causal=causal, block_q=128, block_k=128,
    )
    want = reference_attention(q[:, :small], k[:, :small], v[:, :small], causal=causal)
    max_err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))
    )

    # gradient correctness through the custom-VJP backward kernels —
    # wrong dQ/dK/dV silently corrupts training in a way the forward
    # check cannot see
    def _loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

        return inner

    # grad check runs the backward kernels too — in interpret mode that
    # is ~3-4x the forward work, so shrink the slice further off-TPU
    gsmall = small if on_tpu else min(small, 256)
    small_args = (q[:, :gsmall], k[:, :gsmall], v[:, :gsmall])
    grads_flash = jax.grad(
        _loss(lambda a, b, c: flash_attention(a, b, c, causal=causal,
                                              block_q=128, block_k=128)),
        argnums=(0, 1, 2),
    )(*small_args)
    grads_ref = jax.grad(
        _loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(*small_args)
    grad_rel_err = 0.0
    for a, b in zip(grads_flash, grads_ref):
        norm = max(1e-9, float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
        grad_rel_err = max(
            grad_rel_err,
            float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
            / norm,
        )
    correct = max_err <= tolerance and grad_rel_err <= 5e-2

    def make_chain(op):
        def factory(kreps):
            @jax.jit
            def chain(q, k, v):
                x = q
                for _ in range(kreps):  # data-dependent: output feeds next Q
                    x = op(x, k, v)
                return x.astype(jnp.float32).sum()

            return chain

        return factory

    flops = attention_flops(batch, seq, heads, head_dim, causal)
    fused = lambda q, k, v: flash_attention(q, k, v, causal=causal)
    unfused = lambda q, k, v: reference_attention(q, k, v, causal=causal)
    per_variant = {}
    if on_tpu:
        per_variant["flash"] = flops / chain_delta_seconds(
            make_chain(fused), q, k, v, k1=2, k2=6, iters=iters
        ) / 1e12
    per_variant["xla"] = flops / chain_delta_seconds(
        make_chain(unfused), q, k, v, k1=2, k2=6, iters=iters
    ) / 1e12

    # training path: fwd + custom-VJP backward (the blockwise-recompute
    # kernels), chained through dL/dQ so steps stay data-dependent.
    # ~3.5x forward FLOPs is the standard fwd+bwd attention accounting
    train_tflops = None
    if on_tpu:

        def make_grad_chain(kreps):
            grad = jax.grad(
                lambda q, k, v: jnp.sum(fused(q, k, v).astype(jnp.float32))
            )

            @jax.jit
            def chain(q, k, v):
                x = q
                for _ in range(kreps):
                    x = grad(x, k, v).astype(q.dtype)
                return x.astype(jnp.float32).sum()

            return chain

        train_seconds = chain_delta_seconds(
            make_grad_chain, q, k, v, k1=1, k2=3, iters=iters
        )
        train_tflops = 3.5 * flops / train_seconds / 1e12
    # the headline gauge is the FUSED kernel's own throughput — a fused
    # regression below the XLA baseline must show in the gauge, not be
    # papered over by a max(); off-TPU (interpret mode not timeable)
    # the XLA timing stands in, flagged via details["kernel"]
    kernel = "flash" if "flash" in per_variant else "xla"
    tflops = per_variant[kernel]

    metrics = [
        ProbeMetric(
            "flash-attention-max-error",
            max_err,
            help="Max abs error of fused vs unfused attention",
        ),
        ProbeMetric(
            "flash-attention-grad-rel-error",
            grad_rel_err,
            help="Max relative error of custom-VJP gradients vs autodiff",
        ),
        ProbeMetric(
            "flash-attention-tflops",
            tflops,
            help="Achieved fused attention TFLOP/s",
        ),
    ]
    details = {
        "batch": batch,
        "seq": seq,
        "heads": heads,
        "head_dim": head_dim,
        "causal": causal,
        "max_error": max_err,
        "grad_rel_error": grad_rel_err,
        "kernel": kernel,
        "per_variant_tflops": {k: round(v, 1) for k, v in per_variant.items()},
        "device_kind": device.device_kind,
    }
    ok = correct
    if train_tflops is not None:
        metrics.append(
            ProbeMetric(
                "flash-attention-train-tflops",
                train_tflops,
                help="Effective fwd+bwd TFLOP/s through the custom-VJP kernels",
            )
        )
        details["train_tflops"] = round(train_tflops, 1)
    if "flash" in per_variant and "xla" in per_variant:
        speedup = per_variant["flash"] / per_variant["xla"]
        metrics.append(
            ProbeMetric(
                "flash-attention-speedup",
                speedup,
                help="Fused kernel throughput / unfused XLA attention",
            )
        )
        details["speedup"] = round(speedup, 2)
    rated = rated_for(device.device_kind)
    if rated is not None and on_tpu:
        fraction = tflops / rated.bf16_tflops
        metrics.append(
            ProbeMetric(
                "flash-attention-fraction-of-rated",
                fraction,
                help="Achieved attention TFLOP/s / rated bf16 peak",
            )
        )
        details["rated_tflops"] = rated.bf16_tflops
        details["fraction"] = round(fraction, 3)
        summary = (
            f"flash attention err {max_err:.1e} "
            f"({'OK' if correct else 'MISMATCH'}), {tflops:.0f} TFLOP/s "
            f"= {fraction:.0%} of rated"
            + (f", {details['speedup']}x vs unfused" if "speedup" in details else "")
        )
    else:
        summary = (
            f"flash attention err {max_err:.1e} "
            f"({'OK' if correct else 'MISMATCH'}) on {device.platform} "
            f"(timing via {kernel})"
        )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
