"""CRD manifest generation.

Produces the CustomResourceDefinition for HealthCheck — the controller-gen
output equivalent (reference:
config/crd/bases/activemonitor.keikoproj.io_healthchecks.yaml), with the
same group/version/kind, short names ``hc``/``hcs``, status subresource,
and printer columns (reference: api/v1alpha1/healthcheck_types.go:68-76).

The OpenAPI schema is derived from the pydantic models, so the CRD can
never drift from the code — run ``python -m activemonitor_tpu crd``.
"""

from __future__ import annotations

from typing import Any, Dict

import yaml

from activemonitor_tpu import GROUP, KIND, VERSION
from activemonitor_tpu.api.types import HealthCheckSpec, HealthCheckStatus

PLURAL = "healthchecks"
SINGULAR = "healthcheck"
SHORT_NAMES = ["hc", "hcs"]

PRINTER_COLUMNS = [
    {"name": "LATEST STATUS", "type": "string", "jsonPath": ".status.status"},
    {"name": "SUCCESS CNT  ", "type": "string", "jsonPath": ".status.successCount"},
    {"name": "FAIL CNT", "type": "string", "jsonPath": ".status.failedCount"},
    {
        "name": "REMEDY SUCCESS CNT  ",
        "type": "string",
        "jsonPath": ".status.remedySuccessCount",
    },
    {
        "name": "REMEDY FAIL CNT",
        "type": "string",
        "jsonPath": ".status.remedyFailedCount",
    },
    {"name": "Age", "type": "date", "jsonPath": ".metadata.creationTimestamp"},
]


def _v1_exclusive_bounds(node: Any) -> Any:
    """pydantic emits draft-2020-12 numeric exclusiveMinimum/Maximum;
    apiextensions.k8s.io/v1 JSONSchemaProps declares them as BOOLEANS
    (draft-4 style) beside minimum/maximum — a numeric form makes the
    whole CRD fail to decode at apply time."""
    if isinstance(node, dict):
        out = {k: _v1_exclusive_bounds(v) for k, v in node.items()}
        for exclusive, limit in (
            ("exclusiveMinimum", "minimum"),
            ("exclusiveMaximum", "maximum"),
        ):
            bound = out.get(exclusive)
            if isinstance(bound, (int, float)) and not isinstance(bound, bool):
                out[limit] = bound
                out[exclusive] = True
        return out
    if isinstance(node, list):
        return [_v1_exclusive_bounds(v) for v in node]
    return node


def _collapse_optionals(schema: Dict[str, Any]) -> Dict[str, Any]:
    """Optional fields produce anyOf[{...}, {type: null}] — CRD schemas
    want the plain type with the field simply not required."""

    def collapse(node: Any) -> Any:
        if isinstance(node, dict):
            if "anyOf" in node:
                non_null = [a for a in node["anyOf"] if a.get("type") != "null"]
                if len(non_null) == 1:
                    merged = {k: v for k, v in node.items() if k != "anyOf"}
                    merged.update(non_null[0])
                    return collapse(merged)
            return {k: collapse(v) for k, v in node.items()}
        if isinstance(node, list):
            return [collapse(v) for v in node]
        return node

    return collapse(schema)


def build_crd() -> Dict[str, Any]:
    # keep anyOf through ref-inlining, then collapse the Optional pattern
    spec_schema = HealthCheckSpec.model_json_schema(
        by_alias=True, ref_template="#/$defs/{model}"
    )
    status_schema = HealthCheckStatus.model_json_schema(
        by_alias=True, ref_template="#/$defs/{model}"
    )

    def finalize(raw: Dict[str, Any]) -> Dict[str, Any]:
        defs = raw.pop("$defs", {})

        def inline(node: Any) -> Any:
            if isinstance(node, dict):
                if "$ref" in node:
                    name = node["$ref"].split("/")[-1]
                    return inline(dict(defs[name]))
                return {
                    k: inline(v)
                    for k, v in node.items()
                    if k not in ("title", "default")
                }
            if isinstance(node, list):
                return [inline(v) for v in node]
            return node

        return _v1_exclusive_bounds(_collapse_optionals(inline(raw)))

    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{PLURAL}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": f"{KIND}List",
                "plural": PLURAL,
                "singular": SINGULAR,
                "shortNames": SHORT_NAMES,
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": PRINTER_COLUMNS,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": finalize(spec_schema),
                                "status": finalize(status_schema),
                            },
                        }
                    },
                }
            ],
        },
    }


def crd_yaml() -> str:
    return yaml.safe_dump(build_crd(), sort_keys=False)
