"""Fused flash attention (Pallas) — training-grade single-chip attention.

A fused online-softmax attention kernel with a custom VJP: forward
sweeps K/V blocks per Q block keeping the running max/denominator and
output accumulator in VMEM (the [S, S] score matrix never touches HBM),
and the backward pass recomputes attention probabilities blockwise from
the saved logsumexp — the standard flash-attention recompute strategy,
so training memory stays O(S·D) too. Owning the schedule buys what XLA
fusion cannot guarantee:

- scores/probabilities live entirely in VMEM, forward AND backward
  (HBM traffic O(S·D), not O(S²)) — long sequences stay feasible;
- causal blocks strictly above the diagonal are skipped inside every
  kernel (``pl.when``), so the dead half of the causal grid costs no
  MXU time in either pass.

On non-TPU platforms the kernels run in interpret mode (functionally
identical, slow) so the same code paths are exercised by the CPU test
suite — mirrors ops/stream.py.

Grids put the reduction sweep innermost (TPU grids execute
sequentially, so VMEM scratch carries state across the sweep): forward
and dQ sweep K blocks per Q block; dK/dV sweeps Q blocks per K block.

Complements ops/ring_attention.py: ring attention shards the sequence
ACROSS chips (ICI traffic, sequence parallelism); flash attention fuses
the per-chip block compute. Reference has no analogue (active-monitor
is a Go controller; this is part of the TPU probe library built per
SURVEY.md §5.7-5.8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
# lane width of the m/l scratch rows; TPU vregs are (8, 128) so scalars
# carried per Q row live broadcast across one 128-lane vector
_LANES = 128
# backward blocks default smaller than forward: the backward body holds
# four [bq, bk] f32 temporaries (s, p, dp, ds) against the ~16 MB
# scoped-VMEM limit.
# Tuned from the reproducible sweep `python -m activemonitor_tpu.probes
# flash-attention --sweep` (probes/flash.py sweep(); interleaved
# best-of-rounds against tunnel contention). Measured on v5e at S=2048:
# 512x512 ~25 TFLOP/s effective fwd+bwd, 1024x256 ~111, 2048x256 ~117 —
# the tall-q/narrow-k shape wins decisively; 1024x256 keeps the causal
# block skip meaningful at long sequence lengths. Re-run the sweep on
# new silicon before trusting these.
_BWD_BLOCK_Q = 1024
_BWD_BLOCK_K = 256


def _causal_mask(qi, ki, block_q: int, block_k: int):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return q_pos >= k_pos


def _make_attention_kernel(
    causal: bool, block_q: int, block_k: int, num_k: int, scale: float,
    partial: bool,
):
    """One builder for both forward flavors — identical online-softmax
    body (init, causal visibility, attend, last-visible write point);
    only the finalize differs: the full kernel emits the normalized
    output + logsumexp, the ``partial`` kernel emits the raw
    (accumulator, max, denominator) merge state ring attention combines
    across devices (ops/ring_attention.py)."""
    from jax.experimental import pallas as pl

    def kernel(q_ref, k_ref, v_ref, *rest):
        if partial:
            acc_out, m_out, l_out, acc_ref, m_ref, l_ref = rest
        else:
            o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        # causal: K blocks strictly after this Q block's last row have
        # nothing to attend — skip the matmuls entirely
        q_last = qi * block_q + block_q - 1
        visible = (ki * block_k <= q_last) if causal else (ki >= 0)

        @pl.when(visible)
        def _attend():
            q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
            v = v_ref[0, 0].astype(jnp.float32)
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [block_q, block_k]
            if causal:
                mask = _causal_mask(qi, ki, block_q, block_k)
                s = jnp.where(mask, s, _NEG_INF)

            m_prev = m_ref[:]  # [block_q, LANES] (broadcast rows)
            l_prev = l_ref[:]
            m_curr = jnp.max(s, axis=1)[:, None]  # [block_q, 1]
            m_next = jnp.maximum(m_prev, m_curr)  # [block_q, LANES]
            # rows fully masked so far have m_next == NEG_INF; shifting
            # by it would make exp(NEG_INF - NEG_INF)=1 for masked
            # entries, so clamp the shift (the row's p is 0 either way)
            shift = jnp.maximum(m_next[:, :1], _NEG_INF / 2)
            p = jnp.exp(s - shift)  # [block_q, block_k]
            if causal:
                p = jnp.where(mask, p, 0.0)
            alpha = jnp.exp(m_prev - jnp.maximum(m_next, _NEG_INF / 2))
            l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)[:, None]
            m_ref[:] = m_next
            pv = jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [block_q, D]
            acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

        # write the outputs once, at this Q block's last visible K block
        last_visible = (q_last // block_k) if causal else (num_k - 1)

        @pl.when(ki == last_visible)
        def _finalize():
            if partial:
                acc_out[0, 0] = acc_ref[:]
                m_out[0, 0] = m_ref[:, :1]
                l_out[0, 0] = l_ref[:, :1]
            else:
                l_final = jnp.maximum(l_ref[:, :1], 1e-30)
                o_ref[0, 0] = (acc_ref[:] / l_final).astype(o_ref.dtype)
                # logsumexp of the scaled scores — the backward
                # recompute reconstructs p = exp(s - lse) from this
                lse_ref[0, 0] = (
                    jnp.maximum(m_ref[:, :1], _NEG_INF / 2) + jnp.log(l_final)
                )

    return kernel


def flash_attention_partial(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int = 1024,
    block_k: int = 1024,
):
    """Unnormalized fused attention for one (Q block, KV block) pair in
    ``[batch, seq_q, heads, head_dim]`` layout (ring attention's).

    Returns ``(block_max [B, H, Sq], out_unnormalized [B, Sq, H, D]
    float32, denom [B, H, Sq])`` — the exact contract of ring
    attention's ``_block_attend`` so the K/V ring can merge fused block
    results across devices with its online-softmax recurrence. Not
    differentiable (the ring path is a forward-only probe op); use
    :func:`flash_attention` for training."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = _fit_block(seq_q, block_q)
    block_k = _fit_block(seq_k, block_k)
    num_q, num_k = seq_q // block_q, seq_k // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kernel = _make_attention_kernel(
        causal, block_q, block_k, num_k, scale, partial=True
    )
    spec_q = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_kv = pl.BlockSpec((1, 1, block_k, head_dim), lambda b, h, i, j: (b, h, j, 0))
    spec_row = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))
    acc, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(qt.shape[:3] + (head_dim,), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
        ),
        grid=(batch, heads, num_q, num_k),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=(spec_q, spec_row, spec_row),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return m[..., 0], jnp.swapaxes(acc, 1, 2), l[..., 0]


def _make_dq_kernel(causal: bool, block_q: int, block_k: int, num_k: int, scale: float):
    from jax.experimental import pallas as pl

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc):
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        q_last = qi * block_q + block_q - 1
        visible = (ki * block_k <= q_last) if causal else (ki >= 0)

        @pl.when(visible)
        def _accumulate():
            q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
            v = v_ref[0, 0].astype(jnp.float32)
            do = do_ref[0, 0].astype(jnp.float32)  # [bq, D]
            lse = lse_ref[0, 0]  # [bq, 1]
            delta = delta_ref[0, 0]  # [bq, 1]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            if causal:
                s = jnp.where(_causal_mask(qi, ki, block_q, block_k), s, _NEG_INF)
            p = jnp.exp(s - lse)  # masked entries underflow to 0
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            ds = p * (dp - delta) * scale
            dq_acc[:] += jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        last_visible = (q_last // block_k) if causal else (num_k - 1)

        @pl.when(ki == last_visible)
        def _finalize():
            dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(causal: bool, block_q: int, block_k: int, num_q: int, scale: float):
    from jax.experimental import pallas as pl

    def kernel(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
        dk_ref, dv_ref, dk_acc, dv_acc,
    ):
        ki = pl.program_id(2)  # K block owns this grid row
        qi = pl.program_id(3)  # Q sweep innermost

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        q_last = qi * block_q + block_q - 1
        visible = (ki * block_k <= q_last) if causal else (qi >= 0)

        @pl.when(visible)
        def _accumulate():
            q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
            v = v_ref[0, 0].astype(jnp.float32)
            do = do_ref[0, 0].astype(jnp.float32)  # [bq, D]
            lse = lse_ref[0, 0]  # [bq, 1]
            delta = delta_ref[0, 0]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            if causal:
                s = jnp.where(_causal_mask(qi, ki, block_q, block_k), s, _NEG_INF)
            p = jnp.exp(s - lse)  # [bq, bk]
            dv_acc[:] += jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # p^T @ dO -> [bk, D]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            ds = p * (dp - delta) * scale
            dk_acc[:] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # ds^T @ q -> [bk, D]

        # the LAST Q block attends every K block even under causality,
        # so the write point is unconditional
        @pl.when(qi == num_q - 1)
        def _finalize():
            dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


def _check_blocks(seq: int, block_q: int, block_k: int):
    """Clamp requested blocks to ``seq`` under the same tileability
    rule the backward's ``_fit_block`` enforces: blocks must divide seq
    AND be multiples of 8 (the vreg sublane width). A non-8-multiple
    tile fails Mosaic compilation on real TPU even though CPU interpret
    mode happily runs it — rejecting it here keeps the CPU test suite
    honest about what the hardware accepts."""
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    if seq % block_q or seq % block_k:
        raise ValueError(
            f"seq {seq} not divisible by blocks ({block_q}, {block_k})"
        )
    if block_q % 8 or block_k % 8:
        raise ValueError(
            f"blocks ({block_q}, {block_k}) must be multiples of 8 to tile "
            f"on TPU; pad seq {seq} to a multiple of 8 or use unfused attention"
        )
    # seq%8 with blocks%8==0 is impossible (blocks divide seq), so the
    # two validators (_check_blocks for explicit blocks, _fit_block for
    # adapted ones) enforce one tileability rule between them
    return block_q, block_k


def _fit_block(seq: int, preferred: int) -> int:
    """Largest divisor of ``seq`` that is <= preferred and TPU-tileable
    (a multiple of 8). An 8-aligned ``seq`` always has one (itself, if
    nothing smaller divides); a non-8-aligned ``seq`` has none, and the
    only candidate tile (the whole seq) fails Mosaic compilation on real
    TPU even though CPU interpret mode would run it — raise the same
    clear error everywhere (_check_blocks, flash_attention_partial, the
    backward pass) instead of letting CPU tests green-light a shape the
    hardware rejects. The backward pass uses this so ANY sequence the
    forward accepted can be differentiated — its block preference must
    never re-impose a divisibility the caller's forward blocks did not."""
    for block in range(min(preferred, seq), 7, -1):
        if seq % block == 0 and block % 8 == 0:
            return block
    if seq % 8:
        raise ValueError(
            f"seq {seq} has no TPU-tileable block (blocks must be multiples "
            "of 8); pad seq to a multiple of 8 or use unfused attention"
        )
    return seq


def _forward_bhsd(q, k, v, causal: bool, block_q: int, block_k: int):
    """(out, lse) on [B, H, S, D] arrays; lse is [B, H, S] float32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq, head_dim = q.shape
    block_q, block_k = _check_blocks(seq, block_q, block_k)
    num_q, num_k = seq // block_q, seq // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"

    kernel = _make_attention_kernel(causal, block_q, block_k, num_k, scale, partial=False)
    spec_q = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_kv = pl.BlockSpec((1, 1, block_k, head_dim), lambda b, h, i, j: (b, h, j, 0))
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            # [B, H, S, 1]: the trailing singleton satisfies the TPU
            # block rule (last dim equal to the array's) without padding
            # the row statistics out to a full 128-lane vector
            jax.ShapeDtypeStruct((batch, heads, seq, 1), jnp.float32),
        ),
        grid=(batch, heads, num_q, num_k),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _backward_bhsd(q, k, v, out, lse, dout, causal: bool, block_q=None, block_k=None):
    """dQ/dK/dV on [B, H, S, D] arrays via blockwise recompute.
    ``block_q``/``block_k`` override the tuned defaults (the flash
    probe's ``--sweep`` uses this to re-measure the table the defaults
    cite)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq, head_dim = q.shape
    block_q = _fit_block(seq, block_q or _BWD_BLOCK_Q)
    block_k = _fit_block(seq, block_k or _BWD_BLOCK_K)
    num_q, num_k = seq // block_q, seq // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"

    # D_i = rowsum(dO ∘ O) — cheap elementwise pass XLA fuses; the
    # kernels read it per Q row like the logsumexp
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1,
        keepdims=True,
    )  # [B, H, S, 1]

    spec_q = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_kv = pl.BlockSpec((1, 1, block_k, head_dim), lambda b, h, i, j: (b, h, j, 0))
    spec_row = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        _make_dq_kernel(causal, block_q, block_k, num_k, scale),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(batch, heads, num_q, num_k),
        in_specs=[spec_q, spec_kv, spec_kv, spec_q, spec_row, spec_row],
        out_specs=spec_q,
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    # dK/dV grid: K block outer, Q sweep inner — index maps swap i/j
    spec_q_t = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, j, 0))
    spec_kv_t = pl.BlockSpec((1, 1, block_k, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_row_t = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        _make_dkv_kernel(causal, block_q, block_k, num_q, scale),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        grid=(batch, heads, num_k, num_q),
        in_specs=[spec_q_t, spec_kv_t, spec_kv_t, spec_q_t, spec_row_t, spec_row_t],
        out_specs=(spec_kv_t, spec_kv_t),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, causal: bool, block_q: int, block_k: int):
    out, _ = _forward_bhsd(q, k, v, causal, block_q, block_k)
    return out


def _flash_bhsd_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _forward_bhsd(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bhsd_bwd(causal, block_q, block_k, residuals, dout):
    q, k, v, out, lse = residuals
    dq, dk, dv = _backward_bhsd(q, k, v, out, lse, dout, causal)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    layout: str = "bshd",
) -> jax.Array:
    """Fused attention, differentiable (custom VJP with blockwise
    recompute from the saved logsumexp — flash-attention backward).

    ``layout="bshd"`` takes ``[batch, seq, heads, head_dim]`` (what
    ops/ring_attention.py uses) and transposes to the kernel's native
    ``[batch, heads, seq, head_dim]``; pass ``layout="bhsd"`` when the
    caller already keeps heads-major arrays to skip the transpose passes
    (3 HBM round-trips per call). Sequence length must be divisible by
    the block sizes (blocks are clamped to seq; the backward pass picks
    its own blocks — preferring 1024x256 against the scoped-VMEM limit,
    shrunk to fit any seq the forward accepted).

    Default forward blocks are the measured optimum on v5e (bq=bk=1024:
    ~90 TFLOP/s causal at S=4096, ~4-5x the unfused XLA attention on
    the same chip; bigger blocks exceed the 16 MB scoped-VMEM limit)."""
    if layout == "bshd":
        batch, seq, heads, head_dim = q.shape
    elif layout == "bhsd":
        batch, heads, seq, head_dim = q.shape
    else:
        raise ValueError(f"layout must be bshd or bhsd, got {layout!r}")
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    block_q, block_k = _check_blocks(seq, block_q, block_k)

    # [B, S, H, D] -> [B, H, S, D]: the kernels tile the last two dims
    # (seq-block × head_dim), which is the MXU-friendly layout
    if layout == "bshd":
        qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    else:
        qt, kt, vt = q, k, v

    out = _flash_bhsd(qt, kt, vt, causal, block_q, block_k)
    return jnp.swapaxes(out, 1, 2) if layout == "bshd" else out


def attention_flops(batch: int, seq: int, heads: int, head_dim: int, causal: bool) -> float:
    """Model FLOPs for one attention forward (QK^T + PV matmuls)."""
    pairs = seq * (seq + 1) / 2 if causal else float(seq * seq)
    return 4.0 * head_dim * batch * heads * pairs
