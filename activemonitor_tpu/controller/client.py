"""HealthCheck resource clients.

The reconciler reads/writes HealthCheck objects through this small
interface — backed by etcd via the API server in cluster mode, or by an
in-memory conflict-simulating store everywhere else (the controller
equivalent of the reference's envtest setup, SURVEY.md §4).

Status is a subresource: ``update_status`` writes only ``.status`` and
participates in optimistic concurrency via resourceVersion, so the
conflict-retry discipline of the reference
(reference: healthcheck_controller.go:208-215,1445-1462) is testable.
"""

from __future__ import annotations

import asyncio
import datetime
import itertools
from dataclasses import dataclass
from typing import AsyncIterator, Dict, List, Optional, Protocol

from activemonitor_tpu.api.types import HealthCheck


class ConflictError(Exception):
    """resourceVersion mismatch on write."""


class NotFoundError(Exception):
    """Object does not exist (the reference treats these as storage
    errors to swallow for already-deleted resources,
    healthcheck_controller.go:201-203,1473-1478)."""


@dataclass(frozen=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    namespace: str
    name: str

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


class HealthCheckClient(Protocol):
    async def get(self, namespace: str, name: str) -> Optional[HealthCheck]: ...

    async def list(self, namespace: Optional[str] = None) -> List[HealthCheck]: ...

    async def apply(self, hc: HealthCheck) -> HealthCheck: ...

    async def update_status(self, hc: HealthCheck) -> HealthCheck: ...

    async def delete(self, namespace: str, name: str) -> None: ...

    def watch(self) -> AsyncIterator[WatchEvent]:
        """MUST register/baseline synchronously at call time; the manager
        calls watch() before its boot-resync list so nothing is lost."""
        ...


async def _retry(fn, *, retryable, attempts: int, base_delay: float, clock=None):
    """One exponential-backoff ladder for every retry policy in this
    layer; ``retryable(exc)`` decides what rides, everything else
    propagates immediately. Sleeps on the injected clock when given so
    fake-clock tests drive the backoff."""
    sleep = clock.sleep if clock is not None else asyncio.sleep
    last: Exception | None = None
    for i in range(attempts):
        try:
            return await fn()
        except Exception as e:
            if not retryable(e):
                raise
            last = e
            if i + 1 < attempts:  # no pointless sleep after the final try
                await sleep(base_delay * (2**i))
    raise last  # type: ignore[misc]


async def retry_on_conflict(
    fn, *, attempts: int = 5, base_delay: float = 0.01, clock=None
):
    """Conflict-retry with jittered backoff, the RetryOnConflict shape
    (reference: healthcheck_controller.go:208-215)."""
    return await _retry(
        fn,
        retryable=lambda e: isinstance(e, ConflictError),
        attempts=attempts,
        base_delay=base_delay,
        clock=clock,
    )


# HTTP statuses worth retrying in place: server-side transients. 4xx
# (other than 429) mean the REQUEST is wrong and a retry cannot help.
TRANSIENT_STATUSES = frozenset({429, 500, 502, 503, 504})


def is_transient(e: Exception) -> bool:
    """The one duck-typed transient classification (an exception's
    ``status`` attribute against TRANSIENT_STATUSES) — shared by the
    retry ladder below and the reconciler's watch loops so the two can
    never disagree on what counts as retryable."""
    return getattr(e, "status", None) in TRANSIENT_STATUSES


async def retry_on_transient(
    fn, *, attempts: int = 6, base_delay: float = 0.25, clock=None
):
    """Retry ``fn`` through transient server errors (5xx/429), duck-
    typed on an exception's ``status`` attribute so this layer needs no
    import of the REST client. Built for writes that record work which
    ALREADY HAPPENED (a completed run's status): letting a blip
    propagate turns into a full re-reconcile that re-runs the check —
    duplicate workflow submissions for one scheduled fire (the
    reference shares this shape: its workqueue requeues the whole
    reconcile on any status-write error). Six attempts spread ~8 s of
    backoff; a storm outlasting that degrades to the requeue ladder's
    at-least-once semantics."""
    return await _retry(
        fn,
        retryable=is_transient,
        attempts=attempts,
        base_delay=base_delay,
        clock=clock,
    )


class ShardFilteredClient:
    """Shard-aware view over any :class:`HealthCheckClient`.

    ``list()`` and ``watch()`` surface only checks the ``owns``
    predicate admits — evaluated at DELIVERY time, so ownership changes
    (shard adoption, shed) apply to the live stream without
    re-establishing it. ``get``/``apply``/``update_status``/``delete``
    pass through unfiltered: handoff races legitimately read and write
    across shard boundaries (the write fence, not the client, guards
    those). The CLI's sharded mode uses the Kubernetes client's native
    predicate (``KubernetesHealthCheckClient(owns=...)``, which also
    skips parsing unowned items); this wrapper is for embedders that
    build a sharded ``Manager`` directly on the in-memory/file
    backends, and for the handoff test tiers.
    """

    def __init__(self, inner: HealthCheckClient, owns):
        self._inner = inner
        self._owns = owns  # (namespace, name) -> bool, live

    async def get(self, namespace: str, name: str) -> Optional[HealthCheck]:
        return await self._inner.get(namespace, name)

    async def list(self, namespace: Optional[str] = None) -> List[HealthCheck]:
        return [
            hc
            for hc in await self._inner.list(namespace)
            if self._owns(hc.metadata.namespace, hc.metadata.name)
        ]

    async def apply(self, hc: HealthCheck) -> HealthCheck:
        return await self._inner.apply(hc)

    async def update_status(self, hc: HealthCheck) -> HealthCheck:
        return await self._inner.update_status(hc)

    async def delete(self, namespace: str, name: str) -> None:
        await self._inner.delete(namespace, name)

    def watch(self) -> AsyncIterator[WatchEvent]:
        # register the inner subscription SYNCHRONOUSLY at call time so
        # the wrapper preserves the list-then-watch no-lost-events
        # contract the manager relies on
        inner_iter = self._inner.watch()

        async def gen() -> AsyncIterator[WatchEvent]:
            async for event in inner_iter:
                if self._owns(event.namespace, event.name):
                    yield event

        return gen()

    def __getattr__(self, name):
        # test hooks and backend extras (force_conflicts, ...) pass through
        return getattr(self._inner, name)


class InMemoryHealthCheckClient:
    """In-memory store with resourceVersion CAS and watch events."""

    def __init__(self):
        self._objects: Dict[str, HealthCheck] = {}
        self._rv = itertools.count(1)
        self._uid = itertools.count(1)
        self._watchers: List[asyncio.Queue] = []
        self._force_conflicts = 0  # test hook: fail next N status updates

    # -- test hooks ----------------------------------------------------
    def force_conflicts(self, n: int) -> None:
        self._force_conflicts = n

    # -- CRUD ----------------------------------------------------------
    async def get(self, namespace: str, name: str) -> Optional[HealthCheck]:
        hc = self._objects.get(f"{namespace}/{name}")
        return hc.deepcopy() if hc is not None else None

    async def list(self, namespace: Optional[str] = None) -> List[HealthCheck]:
        return [
            hc.deepcopy()
            for key, hc in sorted(self._objects.items())
            if namespace is None or hc.metadata.namespace == namespace
        ]

    async def apply(self, hc: HealthCheck) -> HealthCheck:
        """Create or update the spec (not status), like kubectl apply."""
        hc = hc.deepcopy()
        if not hc.metadata.name:
            from activemonitor_tpu.engine.base import generate_name

            hc.metadata.name = generate_name(hc.metadata.generate_name or "hc-")
        key = hc.key
        existing = self._objects.get(key)
        if existing is None:
            hc.metadata.uid = f"uid-{next(self._uid)}"
            hc.metadata.creation_timestamp = datetime.datetime.now(
                datetime.timezone.utc
            )
            hc.metadata.resource_version = str(next(self._rv))
            self._objects[key] = hc.deepcopy()
            self._notify("ADDED", hc)
        else:
            existing.spec = hc.spec
            existing.metadata.labels = hc.metadata.labels
            existing.metadata.annotations = hc.metadata.annotations
            existing.metadata.resource_version = str(next(self._rv))
            hc = existing.deepcopy()
            self._notify("MODIFIED", hc)
        return hc.deepcopy()

    async def update_status(self, hc: HealthCheck) -> HealthCheck:
        key = hc.key
        existing = self._objects.get(key)
        if existing is None:
            raise NotFoundError(key)
        if self._force_conflicts > 0:
            self._force_conflicts -= 1
            raise ConflictError(key)
        if (
            hc.metadata.resource_version
            and hc.metadata.resource_version != existing.metadata.resource_version
        ):
            raise ConflictError(
                f"{key}: rv {hc.metadata.resource_version} != {existing.metadata.resource_version}"
            )
        existing.status = hc.status.model_copy(deep=True)
        existing.metadata.resource_version = str(next(self._rv))
        self._notify("MODIFIED", existing)
        return existing.deepcopy()

    async def delete(self, namespace: str, name: str) -> None:
        hc = self._objects.pop(f"{namespace}/{name}", None)
        if hc is None:
            raise NotFoundError(f"{namespace}/{name}")
        self._notify("DELETED", hc)

    # -- watch ---------------------------------------------------------
    def _notify(self, type_: str, hc: HealthCheck) -> None:
        ev = WatchEvent(type=type_, namespace=hc.metadata.namespace, name=hc.metadata.name)
        for q in self._watchers:
            q.put_nowait(ev)

    def watch(self) -> AsyncIterator[WatchEvent]:
        """Registers the subscription SYNCHRONOUSLY (at call time, not at
        first iteration) so no event can fall between creating the watch
        and a subsequent list — the list-then-watch ordering the manager
        relies on."""
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append(q)

        async def gen() -> AsyncIterator[WatchEvent]:
            try:
                while True:
                    yield await q.get()
            finally:
                self._watchers.remove(q)

        return gen()
