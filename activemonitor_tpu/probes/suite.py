"""Aggregate probe suite — the whole battery in one payload.

One workflow, one compile cache, one verdict: runs every applicable
probe and merges their metrics into a single contract line. The
natural payload for a single "is this TPU healthy" HealthCheck; probes
inapplicable to the hardware (rated comparisons on unknown chips,
multi-device checks on one chip) degrade the way they do individually.
"""

from __future__ import annotations

import sys
from typing import List, Optional, Tuple

from activemonitor_tpu.probes.base import ProbeResult


def run(
    quick: bool = False,
    skip: Optional[List[str]] = None,
) -> ProbeResult:
    skip = set(skip or [])
    results: List[Tuple[str, ProbeResult]] = []

    def add(name: str, fn) -> None:
        if name in skip:
            return
        try:
            results.append((name, fn()))
        except Exception as e:  # a crashing probe is a failing probe
            results.append(
                (name, ProbeResult(ok=False, summary=f"{name} crashed: {e!r}"))
            )

    from activemonitor_tpu.probes import (
        compile_smoke,
        decode,
        devices,
        hbm,
        ici,
        matmul,
        memory,
        ring,
        training_step,
    )

    iters = 3 if quick else 5
    add("devices", lambda: devices.run())
    add("memory", lambda: memory.run(probe_gb=0.5 if quick else 1.0))
    add("compile-smoke", lambda: compile_smoke.run(tiny=quick))
    add("matmul", lambda: matmul.run(dim=4096 if quick else 8192, iters=iters))
    add("hbm", lambda: hbm.run(size_mb=128 if quick else 256, iters=iters))
    add("ici-allreduce", lambda: ici.run(size_mb=16 if quick else 64, iters=iters))
    add(
        "ring-attention",
        lambda: ring.run(seq_per_device=256 if quick else 1024, iters=iters),
    )
    add(
        "training-step",
        lambda: training_step.run(tiny=quick, batch_per_device=4, seq=64),
    )
    add(
        "decode",
        lambda: decode.run(tiny=quick, batch=4, prompt_len=8, iters=iters),
    )
    from activemonitor_tpu.probes import dcn

    # informational pass on single-process runs; real coverage on
    # multi-host slices where jax.distributed is initialized
    add("dcn-allreduce", lambda: dcn.run(size_mb=4 if quick else 16, iters=iters))

    metrics = []
    failed = []
    for name, result in results:
        metrics.extend(result.metrics)
        status = "OK " if result.ok else "FAIL"
        print(f"  [{status}] {name}: {result.summary}", file=sys.stderr)
        if not result.ok:
            failed.append(name)
    ok = not failed
    summary = (
        f"all {len(results)} probes passed"
        if ok
        else f"{len(failed)}/{len(results)} probes failed: {', '.join(failed)}"
    )
    return ProbeResult(
        ok=ok,
        summary=summary,
        metrics=metrics,
        details={"probes_run": len(results), "failed": failed},
    )
