"""Flash-attention probe — fused single-chip attention health + perf.

Two verdicts in one probe (the single-chip sibling of the ring probe):

1. correctness — the Pallas fused kernel (ops/flash_attention.py) must
   match unfused reference attention; a mismatch means the Mosaic
   compile or the chip's MXU/VPU path is producing wrong numbers;
2. throughput — achieved attention TFLOP/s of the fused kernel, with
   the unfused XLA attention timed alongside as the speedup baseline.
   A fused/unfused ratio collapsing toward 1 means the kernel stopped
   being fused (toolchain regression) long before absolute numbers
   drift.

Off-TPU the kernel runs in interpret mode: correctness is still checked
(same code path) but timing falls back to the XLA expression, mirroring
the HBM probe's policy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.obs import roofline as roofline_model
from activemonitor_tpu.ops.flash_attention import attention_flops, flash_attention
from activemonitor_tpu.ops.ring_attention import reference_attention
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds


def _apply_fraction_gate(details: dict, fraction: float, min_fraction) -> bool:
    """Record the BASELINE.md fraction-of-rated bar in ``details`` and
    return the verdict. Shared by run() and sweep() so the gate policy
    and the details keys cannot drift between the two probes."""
    if min_fraction is None:
        return True
    details["min_fraction"] = min_fraction
    if fraction < min_fraction:
        details["fraction_gate"] = f"FAILED ({fraction:.3f} < {min_fraction})"
        return False
    details["fraction_gate"] = "passed"
    return True


def sweep(
    batch: int = 4,
    seq: int | None = None,
    heads: int = 8,
    head_dim: int = 128,
    iters: int = 3,
    causal: bool = True,
    rounds: int = 2,
    fwd_blocks: tuple = (256, 512, 1024, 2048),
    bwd_blocks: tuple = ((512, 512), (1024, 256), (2048, 256), (1024, 512)),
    train: bool = True,
    min_fraction: float | None = None,
) -> ProbeResult:
    """(block_q, block_k) → TFLOP/s tables — the measurements the
    kernel defaults in ops/flash_attention.py cite, reproducible on
    demand instead of comment-lore.

    Forward sweeps a square-ish grid of (bq, bk); the backward sweep
    times the dQ + dK/dV kernels DIRECTLY (chained through dout) over
    the candidate (bwd_q, bwd_k) shapes, reporting effective fwd+bwd
    TFLOP/s with the best forward config. ``rounds`` full passes are
    interleaved round-robin and the per-config best kept — on a shared
    chip a single pass can be skewed by a contention burst landing on
    one config (utils/timing.py's drift rule, applied across configs).
    Configs the hardware rejects (scoped-VMEM overflow) are recorded as
    errors, not crashes."""
    from activemonitor_tpu.ops.flash_attention import (
        _backward_bhsd,
        _forward_bhsd,
    )

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    # only the DEFAULT clamps off-TPU (interpret mode: keep the sweep
    # finishable); an explicit seq is honored verbatim — the CLI
    # promises "an explicit --seq always wins" (ADVICE r3)
    if seq is None:
        seq = 2048 if on_tpu else 256
    dtype = jnp.bfloat16
    keys = jax.random.split(jax.random.key(0), 3)
    # kernel-native [B, H, S, D] layout so the sweep times the kernel,
    # not the bshd transposes
    q, k, v = (
        jax.random.normal(kk, (batch, heads, seq, head_dim), dtype) for kk in keys
    )
    flops = attention_flops(batch, seq, heads, head_dim, causal)

    def time_forward(bq, bk):
        def make_chain(reps):
            @jax.jit
            def chain(q, k, v):
                x = q
                for _ in range(reps):
                    x, _ = _forward_bhsd(x, k, v, causal, bq, bk)
                return x.astype(jnp.float32).sum()

            return chain

        return flops / chain_delta_seconds(
            make_chain, q, k, v, k1=1, k2=3, iters=iters
        ) / 1e12

    fwd_table: dict = {}
    fwd_configs = [
        (bq, bk)
        for bq in fwd_blocks
        for bk in fwd_blocks
        if bq <= seq and bk <= seq and seq % bq == 0 and seq % bk == 0
    ]
    for _ in range(rounds):
        for bq, bk in fwd_configs:
            key = f"{bq}x{bk}"
            try:
                tflops = time_forward(bq, bk)
            except Exception as exc:
                fwd_table.setdefault(key, f"error: {str(exc)[:60]}")
                continue
            prev = fwd_table.get(key)
            if not isinstance(prev, float) or tflops > prev:
                fwd_table[key] = tflops

    numeric = {k_: v for k_, v in fwd_table.items() if isinstance(v, float)}
    best_fwd_key = max(numeric, key=numeric.get) if numeric else ""
    best_fwd = numeric.get(best_fwd_key, 0.0)

    metrics = [
        ProbeMetric(
            "flash-sweep-best-fwd-tflops",
            best_fwd,
            help="Best forward TFLOP/s across the block sweep",
        )
    ]
    details = {
        "batch": batch,
        "seq": seq,
        "heads": heads,
        "head_dim": head_dim,
        "causal": causal,
        "rounds": rounds,
        "forward_table_tflops": {
            k_: (round(v, 1) if isinstance(v, float) else v)
            for k_, v in fwd_table.items()
        },
        "best_forward": best_fwd_key,
        "device_kind": device.device_kind,
    }

    train_table: dict = {}
    best_train_key = ""
    if train and best_fwd_key:
        fbq, fbk = (int(x) for x in best_fwd_key.split("x"))
        out, lse = _forward_bhsd(q, k, v, causal, fbq, fbk)
        fwd_seconds = flops / (best_fwd * 1e12)

        def time_backward(bq, bk):
            def make_chain(reps):
                @jax.jit
                def chain(q, k, v, dout):
                    x = dout
                    for _ in range(reps):
                        x, _, _ = _backward_bhsd(
                            q, k, v, out, lse, x, causal,
                            block_q=bq, block_k=bk,
                        )
                    return x.astype(jnp.float32).sum()

                return chain

            return chain_delta_seconds(
                make_chain, q, k, v, out, k1=1, k2=3, iters=iters
            )

        bwd_configs = [
            (bq, bk)
            for bq, bk in bwd_blocks
            if bq <= seq and bk <= seq and seq % bq == 0 and seq % bk == 0
        ]
        for _ in range(rounds):
            for bq, bk in bwd_configs:
                key = f"{bq}x{bk}"
                try:
                    bwd_seconds = time_backward(bq, bk)
                except Exception as exc:
                    train_table.setdefault(key, f"error: {str(exc)[:60]}")
                    continue
                # 3.5x fwd FLOPs: standard attention fwd+bwd accounting
                eff = 3.5 * flops / (fwd_seconds + bwd_seconds) / 1e12
                prev = train_table.get(key)
                if not isinstance(prev, float) or eff > prev:
                    train_table[key] = eff
        numeric_t = {k_: v for k_, v in train_table.items() if isinstance(v, float)}
        if numeric_t:
            best_train_key = max(numeric_t, key=numeric_t.get)
            metrics.append(
                ProbeMetric(
                    "flash-sweep-best-train-tflops",
                    numeric_t[best_train_key],
                    help="Best effective fwd+bwd TFLOP/s (backward-block sweep)",
                )
            )
        details["train_table_tflops"] = {
            k_: (round(v, 1) if isinstance(v, float) else v)
            for k_, v in train_table.items()
        }
        details["best_backward"] = best_train_key

    # the same BASELINE.md bar the non-sweep probe enforces, against
    # the sweep's best forward config (inert off-TPU)
    ok = True
    rated = rated_for(device.device_kind)
    if rated is not None and on_tpu:
        fraction = best_fwd / rated.bf16_tflops
        details["best_fraction_of_rated"] = round(fraction, 3)
        ok = _apply_fraction_gate(details, fraction, min_fraction)
    summary = (
        f"flash sweep @ S={seq}: best fwd {best_fwd:.0f} TFLOP/s ({best_fwd_key})"
        + (
            f", best fwd+bwd {train_table[best_train_key]:.0f} TFLOP/s "
            f"(bwd {best_train_key})"
            if best_train_key
            else ""
        )
        + ("" if on_tpu else " [interpret mode: timings not meaningful]")
    )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)


def run(
    batch: int = 4,
    seq: int | None = None,
    heads: int = 8,
    head_dim: int = 128,
    iters: int = 5,
    causal: bool = True,
    tolerance: float = 2e-2,
    min_fraction: float | None = None,
    roofline: bool = True,
) -> ProbeResult:
    """``min_fraction`` gates the verdict on achieved fwd TFLOP/s as a
    fraction of the chip's rated bf16 peak (BASELINE.md single-chip
    bar, rated.FLASH_FRACTION_BAR) — inert off-TPU where the fraction
    cannot be measured."""
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    # default only — interpret-mode correctness is O(minutes) past 512,
    # but an explicit seq always wins (ADVICE r3)
    if seq is None:
        seq = 4096 if on_tpu else 512
    dtype = jnp.bfloat16
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (
        jax.random.normal(kk, (batch, seq, heads, head_dim), dtype) for kk in keys
    )

    # correctness on a small slice (unfused reference materializes the
    # [S, S] scores — keep it tractable); block sizes forced small so
    # the online-softmax accumulation really iterates
    small = min(seq, 512)
    got = flash_attention(
        q[:, :small], k[:, :small], v[:, :small],
        causal=causal, block_q=128, block_k=128,
    )
    want = reference_attention(q[:, :small], k[:, :small], v[:, :small], causal=causal)
    max_err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))
    )

    # gradient correctness through the custom-VJP backward kernels —
    # wrong dQ/dK/dV silently corrupts training in a way the forward
    # check cannot see
    def _loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

        return inner

    # grad check runs the backward kernels too — in interpret mode that
    # is ~3-4x the forward work, so shrink the slice further off-TPU
    gsmall = small if on_tpu else min(small, 256)
    small_args = (q[:, :gsmall], k[:, :gsmall], v[:, :gsmall])
    grads_flash = jax.grad(
        _loss(lambda a, b, c: flash_attention(a, b, c, causal=causal,
                                              block_q=128, block_k=128)),
        argnums=(0, 1, 2),
    )(*small_args)
    grads_ref = jax.grad(
        _loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(*small_args)
    grad_rel_err = 0.0
    for a, b in zip(grads_flash, grads_ref):
        norm = max(1e-9, float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
        grad_rel_err = max(
            grad_rel_err,
            float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
            / norm,
        )
    # backward accumulates one extra recompute rounding pass over the
    # forward, so its gate is a documented 2.5x of --tolerance (default
    # 2e-2 -> 5e-2) — tightening the flag tightens both verdicts
    grad_tolerance = 2.5 * tolerance
    correct = max_err <= tolerance and grad_rel_err <= grad_tolerance

    # generalized-shape correctness on tiny slices: GQA, packed
    # segments, and a cross-length decode shape. Interpret mode
    # happily runs BlockSpec layouts Mosaic might reject, so running
    # these here means a real-TPU battery validates the generalized
    # kernel paths on silicon, not just the CPU test suite
    gen_errors: dict = {}
    gkeys = jax.random.split(jax.random.key(7), 3)
    gq = jax.random.normal(gkeys[0], (1, 128, 4, 64), dtype)
    gk = jax.random.normal(gkeys[1], (1, 128, 2, 64), dtype)
    gv = jax.random.normal(gkeys[2], (1, 128, 2, 64), dtype)

    def gen_err(name, got_fn, want_fn):
        try:
            got_g = got_fn().astype(jnp.float32)
            want_g = want_fn().astype(jnp.float32)
            gen_errors[name] = float(jnp.max(jnp.abs(got_g - want_g)))
        except Exception as exc:  # pragma: no cover - hardware dependent
            gen_errors[name] = f"error: {str(exc)[:80]}"

    gen_err(
        "gqa",
        lambda: flash_attention(gq, gk, gv, causal=causal, block_q=64, block_k=64),
        lambda: reference_attention(gq, gk, gv, causal=causal),
    )
    seg = jnp.concatenate(
        [jnp.zeros((1, 48), jnp.int32), jnp.ones((1, 80), jnp.int32)], axis=1
    )
    gen_err(
        "packed",
        lambda: flash_attention(
            gq, gk, gv, causal=causal, segment_ids=seg, block_q=64, block_k=64
        ),
        lambda: reference_attention(gq, gk, gv, causal=causal, segment_ids=seg),
    )
    gen_err(
        "cross",
        lambda: flash_attention(
            gq[:, :64], gk, gv, causal=causal, block_q=64, block_k=64
        ),
        lambda: reference_attention(gq[:, :64], gk, gv, causal=causal),
    )
    correct = correct and all(
        isinstance(e, float) and e <= tolerance for e in gen_errors.values()
    )

    def make_chain(op):
        def factory(kreps):
            @jax.jit
            def chain(q, k, v):
                x = q
                for _ in range(kreps):  # data-dependent: output feeds next Q
                    x = op(x, k, v)
                return x.astype(jnp.float32).sum()

            return chain

        return factory

    flops = attention_flops(batch, seq, heads, head_dim, causal)
    fused = lambda q, k, v: flash_attention(q, k, v, causal=causal)
    unfused = lambda q, k, v: reference_attention(q, k, v, causal=causal)
    per_variant = {}
    if on_tpu:
        per_variant["flash"] = flops / chain_delta_seconds(
            make_chain(fused), q, k, v, k1=2, k2=6, iters=iters
        ) / 1e12
    per_variant["xla"] = flops / chain_delta_seconds(
        make_chain(unfused), q, k, v, k1=2, k2=6, iters=iters
    ) / 1e12

    # training path: fwd + custom-VJP backward (the blockwise-recompute
    # kernels), chained through dL/dQ so steps stay data-dependent.
    # ~3.5x forward FLOPs is the standard fwd+bwd attention accounting
    train_tflops = None
    if on_tpu:

        def make_grad_chain(kreps):
            grad = jax.grad(
                lambda q, k, v: jnp.sum(fused(q, k, v).astype(jnp.float32))
            )

            @jax.jit
            def chain(q, k, v):
                x = q
                for _ in range(kreps):
                    x = grad(x, k, v).astype(q.dtype)
                return x.astype(jnp.float32).sum()

            return chain

        train_seconds = chain_delta_seconds(
            make_grad_chain, q, k, v, k1=1, k2=3, iters=iters
        )
        train_tflops = 3.5 * flops / train_seconds / 1e12
    # the headline gauge is the FUSED kernel's own throughput — a fused
    # regression below the XLA baseline must show in the gauge, not be
    # papered over by a max(); off-TPU (interpret mode not timeable)
    # the XLA timing stands in, flagged via details["kernel"]
    kernel = "flash" if "flash" in per_variant else "xla"
    tflops = per_variant[kernel]

    metrics = [
        ProbeMetric(
            "flash-attention-max-error",
            max_err,
            help="Max abs error of fused vs unfused attention",
        ),
        ProbeMetric(
            "flash-attention-grad-rel-error",
            grad_rel_err,
            help="Max relative error of custom-VJP gradients vs autodiff",
        ),
        ProbeMetric(
            "flash-attention-tflops",
            tflops,
            help="Achieved fused attention TFLOP/s",
        ),
    ]
    details = {
        "batch": batch,
        "seq": seq,
        "heads": heads,
        "head_dim": head_dim,
        "causal": causal,
        "max_error": max_err,
        "grad_rel_error": grad_rel_err,
        "tolerance": tolerance,
        "grad_tolerance": grad_tolerance,
        "generalized_max_errors": {
            name: (round(e, 6) if isinstance(e, float) else e)
            for name, e in gen_errors.items()
        },
        "kernel": kernel,
        "per_variant_tflops": {k: round(v, 1) for k, v in per_variant.items()},
        "device_kind": device.device_kind,
    }
    ok = correct
    if train_tflops is not None:
        metrics.append(
            ProbeMetric(
                "flash-attention-train-tflops",
                train_tflops,
                help="Effective fwd+bwd TFLOP/s through the custom-VJP kernels",
            )
        )
        details["train_tflops"] = round(train_tflops, 1)
    if "flash" in per_variant and "xla" in per_variant:
        speedup = per_variant["flash"] / per_variant["xla"]
        metrics.append(
            ProbeMetric(
                "flash-attention-speedup",
                speedup,
                help="Fused kernel throughput / unfused XLA attention",
            )
        )
        details["speedup"] = round(speedup, 2)
    rated = rated_for(device.device_kind)
    if rated is not None and on_tpu:
        fraction = tflops / rated.bf16_tflops
        metrics.append(
            ProbeMetric(
                "flash-attention-fraction-of-rated",
                fraction,
                help="Achieved attention TFLOP/s / rated bf16 peak",
            )
        )
        details["rated_tflops"] = rated.bf16_tflops
        details["fraction"] = round(fraction, 3)
        # evaluate the gate unconditionally: a failing-correctness run
        # must still record min_fraction/fraction_gate in details
        gate_ok = _apply_fraction_gate(details, fraction, min_fraction)
        ok = ok and gate_ok
        summary = (
            f"flash attention err {max_err:.1e} "
            f"({'OK' if correct else 'MISMATCH'}), {tflops:.0f} TFLOP/s "
            f"= {fraction:.0%} of rated"
            + (f", {details['speedup']}x vs unfused" if "speedup" in details else "")
        )
    else:
        summary = (
            f"flash attention err {max_err:.1e} "
            f"({'OK' if correct else 'MISMATCH'}) on {device.platform} "
            f"(timing via {kernel})"
        )
    result = ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
    # roofline verdict under the fraction (obs/roofline.py): the fused
    # kernel's whole contract is one blockwise HBM pass — q/k/v read +
    # out/lse write — which at S=4096 puts intensity far right of the
    # ridge (compute-bound). Analytic cost model by design: XLA's
    # compile-time numbers for a Mosaic custom call say nothing about
    # the kernel's real traffic, and the unfused expression's cost
    # (materialized [S,S] scores) is the wrong algorithm.
    tensor_bytes = batch * seq * heads * head_dim * jnp.dtype(dtype).itemsize
    roofline_model.apply(
        result,
        roofline_model.capture(
            "flash-attention",
            seconds=flops / (tflops * 1e12) if tflops > 0 else 0.0,
            model_flops=float(flops),
            # 3 inputs + output, plus the f32 logsumexp per (b, h, s)
            model_bytes=float(4 * tensor_bytes + batch * heads * seq * 4),
            enabled=roofline,
        ),
    )
    return result
