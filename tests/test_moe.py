"""Expert-parallel MoE tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.ops.moe import (
    init_moe_params,
    moe_ffn_expert_parallel,
    moe_ffn_reference,
)
from activemonitor_tpu.parallel.mesh import make_1d_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_1d_mesh("ep")


@pytest.mark.parametrize("n_experts", [8, 16])
def test_expert_parallel_matches_dense(mesh, n_experts):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=n_experts)
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    got = moe_ffn_expert_parallel(params, x, mesh, "ep")
    want = moe_ffn_reference(params, x)
    assert jnp.max(jnp.abs(got - want)) < 1e-5


def test_expert_parallel_jits(mesh):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=8)
    x = jax.random.normal(jax.random.key(1), (32, 32), jnp.float32)
    fn = jax.jit(lambda p, x: moe_ffn_expert_parallel(p, x, mesh, "ep"))
    out = fn(params, x)
    assert jnp.max(jnp.abs(out - moe_ffn_reference(params, x))) < 1e-5


def test_expert_count_must_divide(mesh):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=6)
    x = jnp.zeros((16, 32), jnp.float32)
    with pytest.raises(ValueError, match="experts"):
        moe_ffn_expert_parallel(params, x, mesh, "ep")


def test_token_count_must_divide(mesh):
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64, n_experts=8)
    x = jnp.zeros((17, 32), jnp.float32)
    with pytest.raises(ValueError, match="tokens"):
        moe_ffn_expert_parallel(params, x, mesh, "ep")


def test_all_experts_used_somewhere(mesh):
    """Sanity: with enough random tokens, routing spreads across experts
    (a degenerate router would silently under-test expert parallelism)."""
    params = init_moe_params(jax.random.key(2), d_model=32, d_ff=64, n_experts=8)
    x = jax.random.normal(jax.random.key(3), (512, 32), jnp.float32)
    expert = jnp.argmax(x @ params["router"], axis=-1)
    assert len(jnp.unique(expert)) >= 6
