"""File artifact reader.

The reference declares the File source in its API
(api/v1alpha1/healthcheck_types.go:134-136) but never implements a
reader — GetArtifactReader falls through to "unknown artifact location"
(store/store.go:15-21). This framework implements it for real
(SURVEY.md §2 #12 lists the gap).
"""

from __future__ import annotations

from pathlib import Path

from activemonitor_tpu.api.types import FileArtifact


class FileReader:
    """Serves a manifest from the local filesystem."""

    def __init__(self, file_artifact: FileArtifact):
        if file_artifact is None or not file_artifact.path:
            raise ValueError("FileArtifact path cannot be empty")
        self._path = Path(file_artifact.path)

    def read(self) -> bytes:
        return self._path.read_bytes()
