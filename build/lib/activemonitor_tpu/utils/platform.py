"""Platform forcing — honoring a CPU run request over a site plugin.

A site-installed PJRT plugin (e.g. a tunneled-device autoregistration
on PYTHONPATH) can override the ``JAX_PLATFORMS`` environment variable;
only the config API outranks it. Every entry point that promises "set
JAX_PLATFORMS=cpu for a virtual mesh" must apply this rule or a "CPU"
run silently lands on — and can wedge against — the remote device.
One implementation, shared by the probe CLI, ``__graft_entry__`` and
``bench.py``, so the trigger conditions cannot drift.
"""

from __future__ import annotations

import os


def force_cpu() -> bool:
    """Unconditionally pin this process to the CPU backend (the config
    API outranks env vars AND site plugins). Safe before or after the
    first jax import; returns False if the config rejects it (backend
    already initialized on another platform)."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return False
    return True


def force_cpu_if_requested(include_flags: bool = False):
    """Apply :func:`force_cpu` when the environment asks for a virtual
    CPU run. Returns True when forced, False when a request was present
    but could not be applied, None when nothing requested it.

    The base trigger is an explicit ``JAX_PLATFORMS=cpu``.
    ``include_flags=True`` additionally triggers on the driver's
    ``--xla_force_host_platform_device_count`` flag (a virtual device
    mesh only the CPU backend provides) — that broad rule belongs to
    the graft-driver contract (``__graft_entry__``), where the ambient
    environment may pin another platform; operator-facing entry points
    like the probe CLI deliberately do NOT use it, because a stale
    XLA_FLAGS in a shell would otherwise silently turn a real-chip
    battery run into CPU interpret-mode numbers labeled as chip
    health."""
    flags = os.environ.get("XLA_FLAGS", "")
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or (
        include_flags and "xla_force_host_platform_device_count" in flags
    ):
        return force_cpu()
    return None
