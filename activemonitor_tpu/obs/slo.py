"""Rolling-window SLO accounting over the result history.

ML Productivity Goodput (PAPERS.md, arXiv:2502.06982) frames fleet
health as availability/goodput over a rolling window rather than the
point-in-time verdict the CR status holds. This module is that math,
kept as pure functions over :class:`~activemonitor_tpu.obs.history.
CheckResult` lists so fake-clock tests assert exact values, plus
:class:`FleetStatus` — the stateful aggregate the reconciler feeds and
the ``/statusz`` endpoint serves.

Definitions (documented in docs/observability.md):

- **window**: results whose finish timestamp lies in
  ``(now - window_seconds, now]``. Results age out of the SLO even
  while they remain in the bounded ring.
- **availability**: successful runs / total runs in the window.
  ``None`` when the window is empty (no verdict beats a made-up one).
- **latency quantiles**: nearest-rank (no interpolation) over the
  window's latencies — ``sorted[ceil(q*n)-1]`` — so a scripted
  sequence yields an exact recorded latency, never a blend.
- **error budget**: the objective allows a failure ratio of
  ``1 - objective`` per window. ``remaining = 1 - observed/allowed``
  (may go negative once the budget is blown — that overdraft is the
  signal, so it is not clamped); ``burn_rate = observed/allowed``
  (1.0 = burning exactly at budget).
- **fleet goodput**: successful runs / total runs across every check's
  own window — run-weighted, so one flapping 10 s check moves the
  number more than a healthy daily check, which is what a prober
  fleet's "useful work fraction" should do.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass
from datetime import datetime
from typing import Dict, List, Optional, Sequence

from activemonitor_tpu.obs import attribution, criticalpath
from activemonitor_tpu.obs.history import CheckResult, ResultHistory
from activemonitor_tpu.obs.trace import current_trace_id
from activemonitor_tpu.resilience.adapt import DECISION_LOG_CAPACITY
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.slo")

# display window when a check declares no slo: block — one hour of
# context on /statusz without opting into budget accounting
DEFAULT_WINDOW_SECONDS = 3600.0

QUANTILES = (0.50, 0.95, 0.99)


@dataclass(frozen=True)
class SLOConfig:
    """A check's declared objective (spec.slo)."""

    objective: float  # target availability ratio in (0, 1)
    window_seconds: float

    @property
    def allowed_failure_ratio(self) -> float:
        return 1.0 - self.objective


def slo_config_from_spec(spec) -> Optional[SLOConfig]:
    """The spec's ``slo:`` block as an :class:`SLOConfig`, or None when
    absent or out of range (the API layer validates; this is the
    defense for dicts that arrived around it)."""
    slo = getattr(spec, "slo", None)
    if slo is None:
        return None
    objective = float(getattr(slo, "objective", 0.0) or 0.0)
    window = float(getattr(slo, "window_seconds", 0.0) or 0.0)
    if not (0.0 < objective < 1.0) or window <= 0:
        return None
    return SLOConfig(objective=objective, window_seconds=window)


def window_results(
    results: Sequence[CheckResult], now: datetime, window_seconds: float
) -> List[CheckResult]:
    """The results that finished within the rolling window
    ``(now - window_seconds, now]`` — exclusive on the left, so a
    result exactly one window old has aged out."""
    return [
        r for r in results if (now - r.ts).total_seconds() < window_seconds
    ]


def availability(results: Sequence[CheckResult]) -> Optional[float]:
    if not results:
        return None
    return sum(1 for r in results if r.ok) / len(results)


def quantile(latencies: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank quantile: the smallest recorded latency such that at
    least ``q`` of the sample is ≤ it. Exact by construction."""
    if not latencies:
        return None
    ordered = sorted(latencies)
    rank = min(len(ordered), max(1, math.ceil(q * len(ordered))))
    return ordered[rank - 1]


def latency_quantiles(
    results: Sequence[CheckResult],
) -> Dict[str, Optional[float]]:
    latencies = [r.latency for r in results]
    return {
        f"p{int(q * 100)}_seconds": quantile(latencies, q) for q in QUANTILES
    }


@dataclass(frozen=True)
class SLOState:
    """One check's SLO verdict over its window."""

    objective: float
    window_seconds: float
    availability: Optional[float]  # None: empty window
    error_budget_remaining: Optional[float]
    burn_rate: Optional[float]

    def to_dict(self) -> dict:
        return {
            "objective": self.objective,
            "window_seconds": self.window_seconds,
            "availability": self.availability,
            "error_budget_remaining": self.error_budget_remaining,
            "burn_rate": self.burn_rate,
        }


def evaluate(
    results: Sequence[CheckResult], config: SLOConfig, now: datetime
) -> SLOState:
    """SLO state over the config's window, exact over the given results."""
    windowed = window_results(results, now, config.window_seconds)
    avail = availability(windowed)
    if avail is None:
        return SLOState(
            objective=config.objective,
            window_seconds=config.window_seconds,
            availability=None,
            error_budget_remaining=None,
            burn_rate=None,
        )
    observed_failure_ratio = 1.0 - avail
    allowed = config.allowed_failure_ratio
    burn = observed_failure_ratio / allowed
    return SLOState(
        objective=config.objective,
        window_seconds=config.window_seconds,
        availability=avail,
        error_budget_remaining=1.0 - burn,
        burn_rate=burn,
    )


def fleet_goodput(
    history: ResultHistory,
    configs: Dict[str, Optional[SLOConfig]],
    now: datetime,
) -> Optional[float]:
    """Run-weighted goodput across the fleet: each check contributes the
    runs inside ITS window (declared, else the display default)."""
    good = total = 0
    for key in history.checks():
        config = configs.get(key)
        window = (
            config.window_seconds if config else DEFAULT_WINDOW_SECONDS
        )
        for result in window_results(history.results(key), now, window):
            total += 1
            good += 1 if result.ok else 0
    if total == 0:
        return None
    return good / total


class FleetStatus:
    """The reconciler-owned aggregate behind ``/statusz``.

    Owns the result history and the last-seen SLO config per check;
    every recorded run updates the SLO gauge families so Prometheus and
    /statusz can never disagree about the same window. Recording never
    raises into the status-write path that feeds it.
    """

    HISTORY_TAIL = 10  # /statusz per-check history excerpt length

    def __init__(self, clock: Optional[Clock] = None, metrics=None):
        self.clock = clock or Clock()
        self.history = ResultHistory(self.clock)
        self.metrics = metrics
        self._configs: Dict[str, Optional[SLOConfig]] = {}
        self._last_status: Dict[str, str] = {}
        # wired by the reconciler (resilience/coordinator.py): the
        # degraded/breaker/remedy-budget state that /statusz and the
        # status CLI report next to the SLO numbers. None (standalone
        # FleetStatus, e.g. unit tests) reports a healthy controller.
        self.resilience = None
        # wired by the reconciler (analysis/engine.py): the baseline &
        # anomaly layer whose per-check verdicts /statusz and the CLI
        # report. None (standalone) reports no analysis blocks.
        self.analysis = None
        # wired by the manager (controller/sharding.py): the shard
        # coordinator whose ownership snapshot rides the fleet block.
        # None (unsharded / standalone) reports sharding: null.
        self.sharding = None
        # wired by the reconciler: the span tracer whose dequeue spans
        # carry the cycle's queue wait — the scheduling-bucket evidence
        # goodput attribution reads at record time. None = no span
        # evidence (standalone), classification still works.
        self.tracer = None
        # wired by the manager (--matrix-state): anything with a
        # ``snapshot()`` returning the scenario matrix's latest round
        # summary (analysis/matrix.py MatrixObservatory or its durable
        # SidecarView). None (no matrix configured) reports matrix: null.
        self.matrix = None
        # wired by the manager (--frontdoor): the probe-as-a-service
        # front door (frontdoor/service.py) whose QPS / coalescing /
        # per-tenant refusal snapshot rides the fleet block. None (no
        # front door) reports frontdoor: null.
        self.frontdoor = None
        # wired by the manager (--journal-dir) via attach_journal: the
        # durable telemetry journal (obs/journal.py) whose segment /
        # stream-count snapshot rides the fleet block. None (no
        # journal) reports journal: null.
        self.journal = None
        # wired by the manager (--profile-on-anomaly): called with
        # (key, reason) when a run's burn rate crosses its threshold,
        # arming one bounded profiler capture of the check's next run.
        # None (profiling off / standalone) — no capture ever fires.
        self.profile_hook = None
        # wired by the reconciler (resilience/adapt.py): the adaptive
        # controller observing every SLO'd run's burn rate + bucket on
        # the record path and serving the /statusz adaptive blocks.
        # None (standalone) — no adaptation, adaptive: null.
        self.adaptive = None
        # wired by the manager (--federation-config): the federation
        # plane (federation/plane.py) whose cluster-registry / routing /
        # global-door snapshot rides the fleet block. None (single
        # cluster) reports federation: null.
        self.federation = None
        # generated_at of the last round exported to the gauges, so the
        # rollup loop re-serving an unchanged sidecar never
        # double-counts the bisect counter
        self._matrix_exported = ""
        # the last fleet attribution rollup (refresh_fleet_goodput), so
        # /statusz serves a block computed over the same windowed runs
        # as the goodput ratio it rides next to
        self._goodput_block = attribution.fleet_attribution(
            self.history, {}, self.clock.now(), DEFAULT_WINDOW_SECONDS
        )

    # -- recording (reconciler status-write path) ----------------------
    def record(
        self,
        hc,
        *,
        ok: bool,
        latency: float,
        workflow: str,
        metrics=None,
        timings=None,
        roofline=None,
    ) -> None:
        try:
            self._record(
                hc,
                ok=ok,
                latency=latency,
                workflow=workflow,
                metrics=metrics,
                timings=timings,
                roofline=roofline,
            )
        except Exception:
            # observability must not fail the status write that feeds it
            log.exception("failed to record result for %s", getattr(hc, "key", "?"))

    def _classify(self, hc, *, ok: bool, metrics, timings, roofline=None) -> tuple:
        """The run's lost-goodput attribution, judged AT RECORD TIME
        while every evidence source is still live: the cycle's dequeue
        span (queue wait), the analysis layer's confirmed per-metric
        verdicts (as of the PREVIOUS run for passing runs — the engine
        observes this run's samples after the record lands, so a
        passing run's display bucket can lag one run; failed runs never
        feed the hysteresis, so their classification has no lag), and
        the breaker's degraded bit. Returns ``(bucket, why)`` —
        ("", "") for an unremarkable ok run. Never raises: attribution
        is garnish on the SLO record, and a classification bug must not
        cost the run its availability/goodput accounting."""
        try:
            return self._classify_inner(
                hc, ok=ok, metrics=metrics, timings=timings, roofline=roofline
            )
        except Exception:
            log.exception(
                "attribution classification failed for %s", getattr(hc, "key", "?")
            )
            return "", ""

    def _classify_inner(
        self, hc, *, ok: bool, metrics, timings, roofline=None
    ) -> tuple:
        key = hc.key
        trace_id = current_trace_id()
        queue_wait = 0.0
        errored_spans = []
        if self.tracer is not None and trace_id:
            # THE queue-wait / span-error definitions live in
            # obs/criticalpath.py, shared with the waterfall's
            # queue_wait stage — one definition, so attribution's
            # scheduling bucket and `am-tpu waterfall` can never
            # disagree about how long this run sat in the queue
            spans = self.tracer.spans_for_trace(trace_id)
            queue_wait = criticalpath.queue_wait(spans)
            errored_spans = criticalpath.errored_span_names(spans)
        anomalies = (
            self.analysis.metric_states(key)
            if self.analysis is not None
            else {}
        )
        anomaly_state = (
            self.analysis.state(key) if self.analysis is not None else "ok"
        )
        degraded = (
            self.resilience.degraded if self.resilience is not None else False
        )
        verdict = attribution.classify_run(
            ok=ok,
            metrics=metrics,
            timings=timings,
            roofline=roofline,
            anomalies=anomalies,
            anomaly_state=anomaly_state,
            queue_wait=queue_wait,
            interval=float(getattr(hc.spec, "repeat_after_sec", 0) or 0),
            degraded_controller=degraded,
            errored_spans=errored_spans,
        )
        if verdict is None:
            return "", ""
        return verdict.bucket, verdict.why

    def _record(
        self,
        hc,
        *,
        ok: bool,
        latency: float,
        workflow: str,
        metrics=None,
        timings=None,
        roofline=None,
    ) -> None:
        key = hc.key
        bucket, why = self._classify(
            hc, ok=ok, metrics=metrics, timings=timings, roofline=roofline
        )
        self.history.record(
            key,
            ok=ok,
            latency=latency,
            workflow=workflow,
            trace_id=current_trace_id(),
            metrics=metrics,
            timings=timings,
            roofline=roofline,
            bucket=bucket,
            why=why,
        )
        self._last_status[key] = "Succeeded" if ok else "Failed"
        config = slo_config_from_spec(hc.spec)
        previous = self._configs.get(key)
        self._configs[key] = config
        # one evaluate per record, shared by every burn-rate consumer —
        # the profile trigger, the adaptive controller, and the gauges
        # must all see the SAME state or they disagree mid-episode
        state = (
            evaluate(self.history.results(key), config, self.clock.now())
            if config is not None
            else None
        )
        if state is not None and self.profile_hook is not None:
            # burn-rate trigger for profile-on-anomaly: a check burning
            # budget faster than it accrues (>1.0) arms one bounded
            # capture of its next run. The hook's own cooldown absorbs
            # the repeat-fire every subsequent failing run would cause.
            if state.burn_rate is not None and state.burn_rate > 1.0:
                try:
                    self.profile_hook(key, "burn_rate")
                except Exception:
                    log.exception("profile hook failed for %s", key)
        if state is not None and self.adaptive is not None:
            # closed-loop control (resilience/adapt.py): the adaptive
            # controller sees every SLO'd run's burn rate with the
            # run's own attribution bucket — the two signals its levers
            # key on, captured at the one place both exist
            try:
                self.adaptive.observe(
                    hc, burn_rate=state.burn_rate, bucket=bucket
                )
            except Exception:
                log.exception("adaptive observe failed for %s", key)
        if self.metrics is None:
            return
        if state is not None:
            if state.availability is not None:
                self.metrics.set_slo(
                    hc.metadata.name,
                    hc.metadata.namespace,
                    availability=state.availability,
                    error_budget_remaining=state.error_budget_remaining,
                    burn_rate=state.burn_rate,
                )
        elif previous is not None:
            # the slo: block was edited off a live check — its series
            # must stop advertising the last pre-edit budget forever
            self.metrics.clear_slo(hc.metadata.name, hc.metadata.namespace)
        # NB: the fleet-wide gauge is deliberately NOT recomputed here —
        # it walks every check's ring, which is O(fleet x capacity) work
        # that doesn't belong on the reconcile path. The manager's
        # goodput loop and /statusz refresh it (refresh_fleet_goodput).

    def refresh_fleet_goodput(self) -> Optional[float]:
        """Recompute the fleet-wide goodput ratio AND its lost-goodput
        attribution in one walk (the decomposition must cover the very
        same windowed runs as the ratio, or conservation breaks), then
        refresh the gauges when a collector is attached. Called off the
        reconcile path: the manager's periodic rollup loop and every
        /statusz build."""
        block = attribution.fleet_attribution(
            self.history, self._configs, self.clock.now(), DEFAULT_WINDOW_SECONDS
        )
        self._goodput_block = block
        ratio = block["ratio"]
        if self.metrics is not None:
            # an empty fleet is vacuously healthy, same convention as
            # the cadence-goodput gauge (all-zero lost buckets agree:
            # they sum to 1 - 1.0)
            self.metrics.set_fleet_goodput(1.0 if ratio is None else ratio)
            self.metrics.set_goodput_attribution(
                block["attribution"],
                block["top"],
                version=attribution.TAXONOMY_VERSION,
            )
        return ratio

    def check_attribution(self, key: str) -> Optional[dict]:
        """One check's windowed attribution block (None when its window
        is empty) — served per check in /statusz and snapshotted into
        flight bundles. Same window rule as the check's SLO display."""
        config = self._configs.get(key)
        window = config.window_seconds if config else DEFAULT_WINDOW_SECONDS
        windowed = window_results(
            self.history.results(key), self.clock.now(), window
        )
        return attribution.summarize_results(windowed)

    def check_waterfalls(self, key: str) -> List[dict]:
        """Per-run waterfalls (obs/criticalpath.py) for the check's
        windowed results, oldest first — each run's trace joined with
        its phase timings while the spans are still in the ring. Runs
        whose trace has aged out of the span ring simply drop out of
        the aggregation (the window quantiles still cover them)."""
        if self.tracer is None:
            return []
        config = self._configs.get(key)
        window = config.window_seconds if config else DEFAULT_WINDOW_SECONDS
        windowed = window_results(
            self.history.results(key), self.clock.now(), window
        )
        waterfalls = []
        for result in windowed:
            if not result.trace_id:
                continue
            waterfall = criticalpath.build_waterfall(
                self.tracer.spans_for_trace(result.trace_id),
                timings=result.timings,
                trace_id=result.trace_id,
            )
            if waterfall is not None:
                waterfalls.append(waterfall)
        return waterfalls

    def check_critical_path(self, key: str) -> Optional[dict]:
        """One check's rolling critical-path block: p50/p95/p99 per
        stage over its windowed waterfalls plus the newest run's full
        decomposition — the ``critical_path`` block /statusz serves and
        the ``healthcheck_critical_path_seconds`` gauges export. None
        when no windowed run still has spans in the ring (or on any
        internal error: the block is garnish on the payload)."""
        try:
            return criticalpath.aggregate_waterfalls(
                self.check_waterfalls(key)
            )
        except Exception:
            log.exception("critical-path aggregation failed for %s", key)
            return None

    def refresh_critical_path_metrics(self, checks) -> None:
        """Export every check's critical-path block into the pinned
        ``healthcheck_critical_path_seconds`` family — driven by the
        manager's goodput loop and every /statusz build (via
        check_summary), so the gauges and the payload always read the
        same aggregation."""
        if self.metrics is None:
            return
        for hc in checks:
            try:
                self.metrics.set_critical_path(
                    hc.metadata.name,
                    hc.metadata.namespace,
                    self.check_critical_path(hc.key),
                )
            except Exception:
                log.exception(
                    "critical-path gauge export failed for %s", hc.key
                )

    def check_roofline(self, key: str) -> Optional[dict]:
        """One check's latest roofline snapshot (obs/roofline.py):
        the newest run that shipped a validated ``roofline`` block —
        per-metric bound/intensity/fraction plus the worst-fraction
        headline — or None when no retained run carried one. Served per
        check in /statusz (`am-tpu roofline` renders it) and
        snapshotted into flight bundles."""
        from activemonitor_tpu.obs import roofline as roofline_model

        return roofline_model.latest_snapshot(self.history.results(key))

    def forget(self, key: str, name: str = "", namespace: str = "") -> None:
        """Deleted check: drop its ring, config, and gauge series —
        and cancel any front-door waiters fanned in on a run that can
        now never record (a typo'd or just-deleted check must fail its
        requests at reconcile speed, not at the reap sweep's bound)."""
        self.history.forget(key)
        self._configs.pop(key, None)
        self._last_status.pop(key, None)
        if self.frontdoor is not None:
            try:
                self.frontdoor.cache.forget(key)
            except Exception:
                log.exception("frontdoor forget failed for %s", key)
        if self.metrics is not None and name:
            self.metrics.clear_slo(name, namespace)
            self.metrics.clear_critical_path(name, namespace)

    # -- /statusz -------------------------------------------------------
    def check_summary(self, hc) -> dict:
        """One check's /statusz entry (schema pinned by contract test)."""
        key = hc.key
        now = self.clock.now()
        results = self.history.results(key)
        config = slo_config_from_spec(hc.spec)
        display_window = (
            config.window_seconds if config else DEFAULT_WINDOW_SECONDS
        )
        windowed = window_results(results, now, display_window)
        last = self.history.last(key)
        # resilience state: the durable .status.state mark wins (it
        # survives restarts); the in-process tracker covers the window
        # before a transition's write lands. Reported lowercase —
        # "healthy" / "flapping" / "quarantined" — like the metric label.
        durable_state = getattr(hc.status, "state", "")
        tracked_state = (
            self.resilience.checks.state(key)
            if self.resilience is not None
            else ""
        )
        state = (durable_state or tracked_state or "Healthy").lower()
        # per-check remedy budget: runs left under remedyRunsLimit, or
        # None when the check has no remedy / no limit configured
        spec = hc.spec
        if spec.remedy_workflow.is_empty() or spec.remedy_runs_limit <= 0:
            remedy_budget = None
        else:
            remedy_budget = max(
                0, spec.remedy_runs_limit - hc.status.remedy_total_runs
            )
        critical_path = self.check_critical_path(key)
        if self.metrics is not None:
            # refresh the gauges from the very block this payload
            # serves, so /statusz and the scrape can never disagree
            self.metrics.set_critical_path(
                hc.metadata.name, hc.metadata.namespace, critical_path
            )
        summary = {
            "key": key,
            "healthcheck": hc.metadata.name,
            "namespace": hc.metadata.namespace,
            "state": state,
            # baseline & anomaly verdict (analysis/engine.py): None when
            # the check declares no analysis: block (or standalone)
            "analysis": (
                self.analysis.summary(hc) if self.analysis is not None else None
            ),
            "remedy_budget_remaining": remedy_budget,
            # lost-goodput attribution over the SAME windowed runs the
            # availability above counts (None when the window is empty)
            # — the per-bucket ratios sum to 1 - availability exactly
            "attribution": attribution.summarize_results(windowed),
            # latest roofline snapshot (obs/roofline.py): the cost-model
            # verdict under the check's fractions; None until a run
            # ships the contract's roofline block
            "roofline": self.check_roofline(key),
            "last_status": hc.status.status
            or self._last_status.get(key, ""),
            "last_trace_id": last.trace_id if last else "",
            # critical-path decomposition (obs/criticalpath.py): rolling
            # per-stage p50/p95/p99 over the windowed runs whose spans
            # are still in the ring, plus the newest run's waterfall;
            # None until a traced run lands. The per-run stage seconds
            # (untracked included) sum to that run's wall span exactly.
            "critical_path": critical_path,
            "runs_recorded": len(results),
            "window": {
                "seconds": display_window,
                "results": len(windowed),
                "availability": availability(windowed),
                **latency_quantiles(windowed),
            },
            "slo": (
                evaluate(results, config, now).to_dict()
                if config is not None
                else None
            ),
            # adaptive-control episode (resilience/adapt.py): which
            # levers currently touch this check and why; None when no
            # lever is engaged (or standalone)
            "adapt": self.check_adapt(key),
            "history": [r.to_dict() for r in self.history.tail(key, self.HISTORY_TAIL)],
        }
        return summary

    def statusz(self, checks) -> dict:
        """The fleet summary payload: the client's current check list
        joined with history/SLO state. Checks deleted from the store
        drop out here even before their reconcile prunes the ring."""
        now = self.clock.now()
        entries = [self.check_summary(hc) for hc in checks]
        # refreshing here keeps the gauge and the payload telling the
        # same number whenever anyone looks
        ratio = self.refresh_fleet_goodput()
        # window-run + anomaly counting shared with the fleet rollup
        # (goodput itself comes from fleet_goodput above: history +
        # declared SLO windows, not the serialized entries)
        agg = aggregate_entries(entries)
        window_runs = agg["window_runs"]
        anomalies = agg["anomalies"]
        if self.resilience is not None:
            resilience = self.resilience.snapshot()
        else:
            resilience = {
                "degraded": False,
                "breaker": None,
                "status_writes_queued": 0,
                "remedy_tokens": None,
            }
        if self.sharding is not None:
            # refresh the per-shard ownership counts against the very
            # check list this payload reports, so the sharding block and
            # the checks array can never disagree
            self.sharding.update_check_counts(checks)
            sharding = self.sharding.snapshot()
        else:
            sharding = None
        return {
            "fleet": {
                "checks": len(entries),
                "window_runs": window_runs,
                "goodput_ratio": ratio,
                # lost-goodput decomposition over the same windowed runs
                # as the ratio above (obs/attribution.py; the per-bucket
                # ratios sum to 1 - goodput ratio — "what is costing us
                # goodput right now", docs/observability.md)
                "goodput": self._goodput_block,
                "generated_at": now.isoformat(),
                "anomalies": anomalies,
                # degraded-mode telemetry (docs/resilience.md): the
                # breaker's verdict, the replay backlog, and the
                # fleet-wide remedy budget
                "degraded": resilience["degraded"],
                "breaker": resilience["breaker"],
                "status_writes_queued": resilience["status_writes_queued"],
                "remedy_tokens": resilience["remedy_tokens"],
                # sharded-fleet ownership (controller/sharding.py): this
                # replica's owned shards and their check counts — the
                # per-shard section rollup_statusz() merges fleet-wide
                "sharding": sharding,
                # scenario-matrix round summary (analysis/matrix.py):
                # per-cell verdicts/rooflines from the latest observed
                # round; null until a matrix source is wired
                # (--matrix-state) and a round has been recorded
                "matrix": self.check_matrix(),
                # front-door ingestion summary (frontdoor/service.py):
                # QPS, coalescing ratios, queue depth, per-tenant
                # refusals; null when no front door is wired
                "frontdoor": self.check_frontdoor(),
                # adaptive-control state (resilience/adapt.py): engaged
                # levers, per-check cadence episodes, front-door
                # degraded mode, and the recent decision log; null when
                # no adaptive controller is wired (standalone)
                "adaptive": self.check_adaptive(),
                # durable telemetry journal (obs/journal.py): segment
                # table, per-stream appended/replayed counts, lag;
                # null when no --journal-dir is wired
                "journal": self.check_journal(),
                # multi-cluster federation (federation/plane.py):
                # cluster registry states, routing, and the global
                # front-door ledger; null when this controller is not
                # federating (--federation-config unset)
                "federation": self.check_federation(),
                # fleet critical-path rollup (obs/criticalpath.py):
                # run-weighted merge of the per-check blocks above —
                # "where do this replica's milliseconds go"; null until
                # a traced run lands anywhere
                "critical_path": criticalpath.merge_critical_path_blocks(
                    [entry.get("critical_path") for entry in entries]
                ),
            },
            "checks": entries,
        }

    def check_frontdoor(self) -> Optional[dict]:
        """The front door's live snapshot, or None (not wired / a
        snapshot error — observability must not fail the payload)."""
        if self.frontdoor is None:
            return None
        try:
            return self.frontdoor.snapshot()
        except Exception:
            log.exception("frontdoor snapshot failed")
            return None

    def check_federation(self) -> Optional[dict]:
        """The federation plane's snapshot, or None (not federating / a
        snapshot error — observability must not fail the payload)."""
        if self.federation is None:
            return None
        try:
            return self.federation.snapshot()
        except Exception:
            log.exception("federation snapshot failed")
            return None

    def check_adaptive(self) -> Optional[dict]:
        """The adaptive controller's fleet snapshot, or None (not wired
        / a snapshot error — observability must not fail the payload)."""
        if self.adaptive is None:
            return None
        try:
            return self.adaptive.snapshot()
        except Exception:
            log.exception("adaptive snapshot failed")
            return None

    def check_adapt(self, key: str) -> Optional[dict]:
        """The adaptive controller's per-check block, or None (no lever
        engaged / not wired / an error)."""
        if self.adaptive is None:
            return None
        try:
            return self.adaptive.check_adapt(key)
        except Exception:
            log.exception("adaptive check block failed for %s", key)
            return None

    def attach_journal(self, journal) -> None:
        """Wire the durable telemetry journal: replay its tail into the
        fresh result history FIRST (restoring the windows the SLO /
        goodput math reads), then subscribe the journal's result tap —
        strictly in that order, so replayed events are never
        re-journaled (the double-count the record/restore split in
        ResultHistory exists to prevent). Replayed results also restore
        the per-check last-status map the /statusz summaries read."""
        self.journal = journal
        journal.replay_into(self.history)
        for key in self.history.checks():
            last = self.history.last(key)
            if last is not None:
                self._last_status[key] = "Succeeded" if last.ok else "Failed"
        self.history.subscribe(journal.record_result)

    def check_journal(self) -> Optional[dict]:
        """The journal's snapshot, or None (not wired / a snapshot
        error — observability must not fail the payload)."""
        if self.journal is None:
            return None
        try:
            return self.journal.snapshot()
        except Exception:
            log.exception("journal snapshot failed")
            return None

    def refresh_journal_metrics(self) -> None:
        """Export the journal's level gauges (segment count, lag
        seconds) into the pinned ``healthcheck_journal_*`` families —
        driven by the manager's goodput loop; the per-event counters
        increment on the append/replay paths themselves. A controller
        without ``--journal-dir`` is a no-op."""
        if self.journal is None:
            return
        try:
            self.journal.export_gauges()
        except Exception:
            log.exception("journal gauge export failed")

    def check_matrix(self) -> Optional[dict]:
        """The matrix source's latest round summary, or None (no source
        wired / no round recorded / a source error — observability must
        not fail the payload that carries it)."""
        if self.matrix is None:
            return None
        try:
            return self.matrix.snapshot()
        except Exception:
            log.exception("matrix snapshot failed")
            return None

    def refresh_matrix_metrics(self) -> None:
        """Export the matrix source's latest round into the pinned
        ``healthcheck_matrix_*`` families — at most once per round
        (keyed on the round's ``generated_at``, so the bisect counter
        never double-counts a re-served sidecar). Called from the
        manager's rollup loop; a controller without ``--matrix-state``
        is a no-op."""
        if self.matrix is None or self.metrics is None:
            return
        snapshot = self.check_matrix()
        if not snapshot:
            return
        stamp = str(snapshot.get("generated_at") or "")
        if stamp and stamp == self._matrix_exported:
            return
        self._matrix_exported = stamp
        try:
            self.metrics.record_matrix_round(snapshot)
        except Exception:
            log.exception("matrix metrics export failed")


def aggregate_entries(entries) -> dict:
    """Window-run and anomaly-state counting over ``/statusz`` check
    entries, shared by :meth:`FleetStatus.statusz` and
    :func:`rollup_statusz` so the per-replica payload and the fleet
    rollup the runbook compares it against count these two by one rule.
    (Goodput is NOT computed here: each replica derives it from its
    result history + declared SLO windows — ``fleet_goodput`` — and the
    rollup averages those replica ratios rather than re-deriving a
    different number from the serialized entries.)"""
    total = 0
    anomalies = {"warning": 0, "degraded": 0}
    for entry in entries:
        window = entry.get("window") or {}
        total += int(window.get("results") or 0)
        analysis = entry.get("analysis")
        if analysis and analysis.get("state") in anomalies:
            anomalies[analysis["state"]] += 1
    return {"window_runs": total, "anomalies": anomalies}


def shard_sort_key(shard) -> int:
    """Numeric sort key for stringly-keyed shard ids (JSON maps): a
    plain string sort reads 0,1,10,11,2,... on 10+-shard fleets. Shared
    by the rollup here and the CLI status table."""
    try:
        return int(shard)
    except (TypeError, ValueError):
        return -1


MERGE_LEVEL_REPLICA = "replica"
MERGE_LEVEL_CLUSTER = "cluster"


def merge_blocks(
    payloads: Sequence[dict], *, level: str = MERGE_LEVEL_REPLICA
) -> dict:
    """The level-agnostic half of the ``/statusz`` merge: every fleet
    field whose math is the same whether the inputs are sharded
    REPLICAS of one cluster or whole CLUSTERS of a federation. One seam
    so the cluster-level merge (``federation/rollup.py``) reuses the
    lookup-weighted front-door ratios, the run-weighted goodput /
    attribution merge, and the skew fallbacks instead of duplicating
    them — :func:`rollup_statusz` keeps only the genuinely
    replica-shaped parts (check dedupe, shard ownership).

    ``level`` is echoed back and picks the meaning of ``replicas``: at
    replica level each payload IS one replica; at cluster level each
    payload is already a rollup carrying its own ``replicas`` count, so
    the federation total sums them (a payload without the count — an
    old binary — counts as one).

    Merge rules, identical at both levels:

    - ``goodput_ratio``: run-weighted mean of the inputs' own ratios —
      the same definition a single /statusz reports, so the number does
      not change meaning with how many units answered.
    - ``goodput`` attribution: merged run-weighted; a payload WITHOUT
      the block (old binary mid rolling update — replica or whole
      cluster) conserves by landing its whole lost share in `unknown`.
    - ``breaker``: worst-state-wins (unknown state ranks worst —
      better to over-alarm than hide a breaker the renderer doesn't
      recognize); ``degraded`` is any-unit; ``generated_at`` is the
      newest stamp; ``status_writes_queued`` / ``remedy_tokens`` sum.
    - ``matrix``: whole-round evidence — the newest round wins, units
      without a matrix source report null and never displace one.
    - ``frontdoor`` / ``journal`` / ``adaptive``: the block-wise merges
      below (counters sum, ratios re-derive lookup-weighted, worst lag,
      first restore warning).
    - ``critical_path``: run-weighted merge with the version-skew
      fallback — a unit serving no block books its windowed runs' whole
      latency as ``untracked``, never silently dropped.
    """
    fleet_blocks: List[dict] = []  # per-unit fleet dicts, for goodput merge
    replicas = 0
    degraded = False
    status_writes_queued = 0
    window_runs = 0
    generated_at = ""
    breaker = None
    breaker_rank = {"closed": 0, "half-open": 1, "open": 2}
    remedy_tokens = None
    matrix_block = None
    frontdoor_blocks: List[dict] = []
    journal_blocks: List[dict] = []
    adaptive_blocks: List[dict] = []
    critical_path_blocks: List[dict] = []
    goodput_weighted = goodput_runs = 0.0
    for payload in payloads:
        fleet = payload.get("fleet") or {}
        fleet_blocks.append(fleet)
        replicas += int(fleet.get("replicas") or 1)
        unit_ratio = fleet.get("goodput_ratio")
        unit_runs = int(fleet.get("window_runs") or 0)
        window_runs += unit_runs
        if unit_ratio is not None and unit_runs > 0:
            goodput_weighted += unit_ratio * unit_runs
            goodput_runs += unit_runs
        degraded = degraded or bool(fleet.get("degraded"))
        status_writes_queued += int(fleet.get("status_writes_queued") or 0)
        generated_at = max(generated_at, str(fleet.get("generated_at") or ""))
        unit_breaker = fleet.get("breaker")
        if unit_breaker is not None:
            rank = breaker_rank.get(str(unit_breaker.get("state")), 3)
            if breaker is None or rank > breaker_rank.get(
                str(breaker.get("state")), 3
            ):
                breaker = unit_breaker
        unit_tokens = fleet.get("remedy_tokens")
        if unit_tokens is not None:
            # per-unit buckets sum to the merged total remedy budget
            remedy_tokens = (remedy_tokens or 0.0) + float(unit_tokens)
        unit_matrix = fleet.get("matrix")
        if isinstance(unit_matrix, dict) and (
            matrix_block is None
            or str(unit_matrix.get("generated_at") or "")
            > str(matrix_block.get("generated_at") or "")
        ):
            matrix_block = unit_matrix
        unit_frontdoor = fleet.get("frontdoor")
        if isinstance(unit_frontdoor, dict):
            frontdoor_blocks.append(unit_frontdoor)
        unit_journal = fleet.get("journal")
        if isinstance(unit_journal, dict):
            journal_blocks.append(unit_journal)
        unit_adaptive = fleet.get("adaptive")
        if isinstance(unit_adaptive, dict):
            adaptive_blocks.append(unit_adaptive)
        unit_critical_path = fleet.get("critical_path")
        if not isinstance(unit_critical_path, dict):
            # version skew: an old binary reports no block (or null) —
            # book its windowed runs' whole latency as untracked
            unit_critical_path = criticalpath.skew_block(payload)
        if unit_critical_path:
            critical_path_blocks.append(unit_critical_path)
    return {
        "level": level,
        "replicas": replicas,
        "window_runs": window_runs,
        "goodput_ratio": (
            (goodput_weighted / goodput_runs) if goodput_runs else None
        ),
        "goodput": attribution.merge_goodput_blocks(fleet_blocks),
        "generated_at": generated_at,
        "degraded": degraded,
        "breaker": breaker,
        "status_writes_queued": status_writes_queued,
        "remedy_tokens": remedy_tokens,
        "matrix": matrix_block,
        "frontdoor": merge_frontdoor_blocks(frontdoor_blocks),
        "adaptive": merge_adaptive_blocks(adaptive_blocks),
        "journal": merge_journal_blocks(journal_blocks),
        "critical_path": criticalpath.merge_critical_path_blocks(
            critical_path_blocks
        ),
    }


def rollup_statusz(payloads: Sequence[dict]) -> dict:
    """Merge per-replica ``/statusz`` payloads into ONE fleet view.

    Each sharded replica serves its own shards' checks; the operator
    (or a dashboard) collects every replica's payload and feeds them
    here. Checks are deduped by key (a handoff in flight may briefly
    double-report; first-seen wins), fleet goodput is the run-weighted
    mean of the replicas' own ratios (same definition as a single
    replica's /statusz), degraded is any-replica, and
    the sharding sections merge into ``shards`` / ``owners`` /
    ``checks_per_shard`` — whose counts sum to the merged check total
    whenever every shard has exactly one owner (the invariant the
    handoff soak pins before and after a kill).

    The level-agnostic fields (goodput + attribution, breaker, matrix,
    frontdoor/journal/adaptive/critical-path blocks) come from
    :func:`merge_blocks`, shared with the federation's cluster-level
    merge; only check dedupe and shard ownership live here, because
    clusters don't share a shard ring. (During a handoff a briefly
    double-reported check weighs in twice in the run-weighted goodput,
    consistent with the summed per-shard counts: the overlap is the
    signal.)
    """
    shared = merge_blocks(payloads, level=MERGE_LEVEL_REPLICA)
    merged: Dict[str, dict] = {}
    owners: Dict[str, str] = {}  # shard id -> owning replica identity
    checks_per_shard: Dict[str, int] = {}
    shards = 0
    saw_sharding = False
    fenced_writes = 0
    for payload in payloads:
        fleet = payload.get("fleet") or {}
        sharding = fleet.get("sharding")
        if sharding:
            saw_sharding = True
            shards = max(shards, int(sharding.get("shards") or 0))
            identity = str(sharding.get("identity") or "")
            fenced_writes += int(sharding.get("fenced_writes") or 0)
            for shard in sharding.get("owned") or []:
                owners[str(shard)] = identity
            for shard, count in (sharding.get("checks_per_shard") or {}).items():
                # SUMMED, not last-wins: while a handoff is in flight two
                # replicas may both claim a shard, and the overlap must
                # surface as counts exceeding the deduped check total —
                # that divergence IS the double-ownership signal
                checks_per_shard[str(shard)] = (
                    checks_per_shard.get(str(shard), 0) + int(count)
                )
        for entry in payload.get("checks") or []:
            key = entry.get("key", "")
            if key not in merged:
                merged[key] = entry
    entries = [merged[key] for key in sorted(merged)]
    agg = aggregate_entries(entries)
    if saw_sharding:
        sharding_block = {
            "shards": shards,
            "owners": {
                k: owners[k] for k in sorted(owners, key=shard_sort_key)
            },
            "checks_per_shard": {
                k: checks_per_shard[k]
                for k in sorted(checks_per_shard, key=shard_sort_key)
            },
            "fenced_writes": fenced_writes,
        }
    else:
        # a classic --leader-elect fleet: every replica reported
        # sharding=null, and so must the rollup (a truthy empty block
        # would render a bogus SHARDS line in the status table)
        sharding_block = None
    return {
        "fleet": {
            "replicas": len(payloads),
            "checks": len(entries),
            "window_runs": agg["window_runs"],
            "goodput_ratio": shared["goodput_ratio"],
            "goodput": shared["goodput"],
            "generated_at": shared["generated_at"],
            "degraded": shared["degraded"],
            "breaker": shared["breaker"],
            "status_writes_queued": shared["status_writes_queued"],
            "remedy_tokens": shared["remedy_tokens"],
            "anomalies": agg["anomalies"],
            "sharding": sharding_block,
            "matrix": shared["matrix"],
            "frontdoor": shared["frontdoor"],
            "adaptive": shared["adaptive"],
            "journal": shared["journal"],
            "critical_path": shared["critical_path"],
        },
        "checks": entries,
    }


def merge_adaptive_blocks(blocks: Sequence[dict]) -> Optional[dict]:
    """Merge per-replica adaptive-control snapshots into one fleet
    block: lever counts SUM (each replica adapts its own checks),
    ``engaged`` is any-replica, the per-check cadence/placement maps
    union first-seen (a check is reconciled by one replica, same dedupe
    rule as the checks array), the front-door sub-block reports the
    widest ceiling any replica runs, and the decision logs interleave
    by timestamp (newest-last, capped at one replica's log length).
    None when no replica reported an adaptive controller."""
    if not blocks:
        return None
    levers: Dict[str, int] = {}
    cadence: Dict[str, dict] = {}
    placement: Dict[str, str] = {}
    frontdoor = {
        "engaged": False,
        "since": None,
        "freshness_ceiling": None,
        "shed_factor": None,
    }
    recent: List[dict] = []
    for block in blocks:
        for lever, count in (block.get("levers") or {}).items():
            levers[str(lever)] = levers.get(str(lever), 0) + int(count or 0)
        for key, episode in (block.get("cadence") or {}).items():
            cadence.setdefault(str(key), episode)
        for key, cohort in (block.get("placement") or {}).items():
            placement.setdefault(str(key), cohort)
        replica_fd = block.get("frontdoor") or {}
        if replica_fd.get("engaged"):
            frontdoor["engaged"] = True
            if frontdoor["since"] is None:
                frontdoor["since"] = replica_fd.get("since")
            if replica_fd.get("shed_factor") is not None:
                frontdoor["shed_factor"] = replica_fd.get("shed_factor")
        ceiling = replica_fd.get("freshness_ceiling")
        if ceiling is not None:
            frontdoor["freshness_ceiling"] = max(
                float(frontdoor["freshness_ceiling"] or 0.0), float(ceiling)
            )
        recent.extend(
            e for e in (block.get("recent") or []) if isinstance(e, dict)
        )
    recent.sort(key=lambda e: str(e.get("ts") or ""))
    recent = recent[-DECISION_LOG_CAPACITY:]
    return {
        "engaged": any(levers.values()),
        "levers": levers,
        "cadence": {k: cadence[k] for k in sorted(cadence)},
        "placement": {k: placement[k] for k in sorted(placement)},
        "frontdoor": frontdoor,
        "recent": recent,
    }


def merge_journal_blocks(blocks: Sequence[dict]) -> Optional[dict]:
    """Merge per-replica journal snapshots into one fleet block: the
    per-stream appended/replayed counters, drops, compactions and
    segment counts SUM (each replica journals its own directory), lag
    is the fleet's WORST (the staleness alert keys on the laggiest
    replica), and the first restore warning seen surfaces — a replica
    that restored fresh must not be hidden by healthy peers. None when
    no replica reported a journal."""
    if not blocks:
        return None
    appended: Dict[str, int] = {}
    replayed: Dict[str, int] = {}
    dropped = compacted = segment_count = 0
    lag = 0.0
    restore_warning = None
    for block in blocks:
        for stream, count in (block.get("appended") or {}).items():
            appended[str(stream)] = appended.get(str(stream), 0) + int(count)
        for stream, count in (block.get("replayed") or {}).items():
            replayed[str(stream)] = replayed.get(str(stream), 0) + int(count)
        dropped += int(block.get("dropped") or 0)
        compacted += int(block.get("compacted_segments") or 0)
        segment_count += int(block.get("segment_count") or 0)
        lag = max(lag, float(block.get("lag_seconds") or 0.0))
        if restore_warning is None and block.get("restore_warning"):
            restore_warning = block["restore_warning"]
    return {
        "replicas": len(blocks),
        "segment_count": segment_count,
        "appended": {s: appended[s] for s in sorted(appended)},
        "replayed": {s: replayed[s] for s in sorted(replayed)},
        "dropped": dropped,
        "compacted_segments": compacted,
        "lag_seconds": lag,
        "restore_warning": restore_warning,
    }


def merge_frontdoor_blocks(blocks: Sequence[dict]) -> Optional[dict]:
    """Merge per-replica front-door snapshots into one fleet block:
    QPS, request/refusal counts, and queue depths SUM (each replica's
    door serves its own slice of the traffic), coalescing ratios
    re-derive lookup-weighted from the summed outcome counts, degraded
    is any-replica, and conservation_ok only if every replica's own
    ledger balanced. None when no replica reported a front door."""
    if not blocks:
        return None
    requests = {
        "submitted": 0,
        "refused": 0,
        "cache_hits": 0,
        "coalesced_joins": 0,
        "probe_runs": 0,
    }
    tenants: Dict[str, dict] = {}
    qps = 0.0
    queue_depth = parked = inflight = reaped = 0
    degraded = False
    conservation_ok = True
    freshness: Optional[dict] = None
    for block in blocks:
        qps += float(block.get("qps") or 0.0)
        queue_depth += int(block.get("queue_depth") or 0)
        parked += int(block.get("parked") or 0)
        inflight += int(block.get("inflight_runs") or 0)
        reaped += int(block.get("reaped_runs") or 0)
        degraded = degraded or bool(block.get("degraded"))
        conservation_ok = conservation_ok and bool(
            block.get("conservation_ok", True)
        )
        # two-ceiling freshness state: clamp counts sum; the ceiling is
        # the widest any replica runs (widened = any). Absent on
        # pre-upgrade replicas, so the merged block may stay None.
        replica_freshness = block.get("freshness")
        if isinstance(replica_freshness, dict):
            if freshness is None:
                freshness = {
                    "default": replica_freshness.get("default"),
                    "ceiling": float(
                        replica_freshness.get("ceiling") or 0.0
                    ),
                    "widened": bool(replica_freshness.get("widened")),
                    "clamped": int(replica_freshness.get("clamped") or 0),
                }
            else:
                freshness["ceiling"] = max(
                    freshness["ceiling"],
                    float(replica_freshness.get("ceiling") or 0.0),
                )
                freshness["widened"] = freshness["widened"] or bool(
                    replica_freshness.get("widened")
                )
                freshness["clamped"] += int(
                    replica_freshness.get("clamped") or 0
                )
        for field_name in requests:
            requests[field_name] += int(
                (block.get("requests") or {}).get(field_name) or 0
            )
        for tenant, row in (block.get("tenants") or {}).items():
            merged_row = tenants.setdefault(
                str(tenant),
                {"submitted": 0, "refused": 0, "refusals": {}, "clamped": 0},
            )
            merged_row["submitted"] += int(row.get("submitted") or 0)
            merged_row["refused"] += int(row.get("refused") or 0)
            merged_row["clamped"] += int(row.get("clamped") or 0)
            for reason, count in (row.get("refusals") or {}).items():
                merged_row["refusals"][str(reason)] = merged_row[
                    "refusals"
                ].get(str(reason), 0) + int(count)
    # lookup-weighted coalescing over the fleet: parked demand is still
    # a miss the cache couldn't absorb, same rule as a single replica
    hits = requests["cache_hits"]
    joins = requests["coalesced_joins"]
    misses = requests["probe_runs"] + parked
    lookups = hits + joins + misses
    coalescing = {
        "hit": hits / lookups if lookups else 0.0,
        "miss": misses / lookups if lookups else 0.0,
        "join": joins / lookups if lookups else 0.0,
        "lookups": lookups,
    }
    return {
        "qps": qps,
        "coalescing": coalescing,
        "queue_depth": queue_depth,
        "parked": parked,
        "inflight_runs": inflight,
        "reaped_runs": reaped,
        "degraded": degraded,
        "conservation_ok": conservation_ok,
        "freshness": freshness,
        "requests": requests,
        "tenants": {t: tenants[t] for t in sorted(tenants)},
    }
