"""Real-apiserver conformance: recorded wire-shape fixtures, replayed.

The reference proves its controller against a REAL `kube-apiserver` +
`etcd` on every CI run (envtest,
/root/reference/internal/controllers/suite_test.go:67-134). No
Kubernetes binaries exist in this sandbox, so the equivalent evidence
is built in two directions around a fixture corpus
(tests/fixtures/apiserver/*.json — real apiserver response shapes with
per-fixture provenance):

1. **Client conformance** — the REAL ``KubeApi`` client is driven
   against a replay server that answers with the fixture bytes
   (including adversarially-chunked watch streams), asserting the
   client's error mapping, watch framing, and review handling against
   the real wire format rather than the in-repo stub's.
2. **Stub conformance** — the in-repo ``StubApiServer`` (which the
   whole cluster-mode test tier trusts) is held to the SAME fixtures:
   each scenario's live stub response must carry the real shape
   (Status kind/apiVersion/metadata, reason, code). The stub can no
   longer drift from apiserver semantics without a test failing.

``docs/conformance.md`` inventories which semantics are fixture-backed
vs still stub-assumed, and ``hack/capture_apiserver_fixtures.sh``
regenerates the corpus from a live cluster when one is reachable.
"""

import json
from pathlib import Path

import pytest

from activemonitor_tpu.kube import KubeApi, KubeConfig
from activemonitor_tpu.kube.client import ApiError
from activemonitor_tpu.kube.stub import StubApiServer

FIXTURE_DIR = Path(__file__).resolve().parent / "fixtures" / "apiserver"
FIXTURES = {
    path.stem: json.loads(path.read_text())
    for path in sorted(FIXTURE_DIR.glob("*.json"))
}


def test_fixture_corpus_is_wellformed():
    assert len(FIXTURES) >= 10
    for name, fx in FIXTURES.items():
        assert fx["name"] == name
        # provenance must be declared — hand-transcribed (the committed
        # corpus) or machine-captured (after the upgrade script ran)
        src = fx.get("source", "").lower()
        assert "transcribed" in src or "machine-captured" in src
        assert "request" in fx
        assert "response" in fx or "stream" in fx
        assert "client_expect" in fx


class ReplayServer:
    """Answers every request with one fixture's recorded response.

    ``chunking`` shapes how watch streams hit the socket: "line" (one
    write per event line), "single" (whole stream in one write), or
    "split" (7-byte writes straddling line boundaries) — the client
    must frame identically in all three.
    """

    def __init__(self, fixture: dict, chunking: str = "line"):
        self.fixture = fixture
        self.chunking = chunking
        self._runner = None
        self.url = ""

    async def start(self) -> str:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        host, port = site._server.sockets[0].getsockname()[:2]
        self.url = f"http://{host}:{port}"
        return self.url

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def _handle(self, request):
        from aiohttp import web

        req = self.fixture["request"]
        assert request.method == req["method"], (
            f"fixture {self.fixture['name']}: got {request.method} "
            f"{request.path}, recorded {req['method']} {req['path']}"
        )
        assert request.path == req["path"]
        if "stream" in self.fixture:
            payload = b"".join(
                json.dumps(ev).encode() + b"\n" for ev in self.fixture["stream"]
            )
            resp = web.StreamResponse()
            resp.content_type = "application/json"
            await resp.prepare(request)
            if self.chunking == "single":
                await resp.write(payload)
            elif self.chunking == "split":
                for i in range(0, len(payload), 7):
                    await resp.write(payload[i : i + 7])
            else:
                for line in payload.splitlines(keepends=True):
                    await resp.write(line)
            return resp
        recorded = self.fixture["response"]
        return web.json_response(recorded["body"], status=recorded["status"])


async def _drive_client(fixture: dict, chunking: str = "line"):
    """Run the real KubeApi against the fixture; return (result, error)."""
    server = ReplayServer(fixture, chunking)
    await server.start()
    api = KubeApi(KubeConfig(server=server.url))
    req = fixture["request"]
    try:
        if "stream" in fixture:
            events = []
            query = req.get("query", {})
            try:
                async for ev in api.watch(
                    req["path"], resource_version=query.get("resourceVersion", "")
                ):
                    events.append(ev)
            except ApiError as exc:
                return events, exc
            return events, None
        try:
            result = await api.request(
                req["method"], req["path"], body=req.get("body")
            )
        except ApiError as exc:
            return None, exc
        return result, None
    finally:
        await api.close()
        await server.stop()


def _check_error(expect: dict, err: ApiError):
    assert err is not None, "recorded response is an error; client returned none"
    assert err.status == expect["error_status"]
    if "reason_contains" in expect:
        assert expect["reason_contains"] in err.reason
    if expect.get("not_found"):
        assert err.not_found
    if expect.get("conflict"):
        assert err.conflict
    # the full recorded Status body must survive into the exception so
    # callers can branch on reason (AlreadyExists vs Conflict)
    if isinstance(err.body, dict):
        assert err.body.get("kind") == "Status"
        assert err.body.get("reason")


@pytest.mark.asyncio
@pytest.mark.parametrize(
    "name",
    [n for n, f in FIXTURES.items() if "error_status" in f["client_expect"]],
)
async def test_client_maps_recorded_errors(name):
    fixture = FIXTURES[name]
    result, err = await _drive_client(fixture)
    _check_error(fixture["client_expect"], err)


@pytest.mark.asyncio
async def test_client_parses_recorded_delete_success():
    fixture = FIXTURES["delete_success"]
    result, err = await _drive_client(fixture)
    assert err is None
    assert result["kind"] == "Status" and result["status"] == "Success"
    assert result["details"]["name"] == "demo"


@pytest.mark.asyncio
async def test_client_parses_recorded_list_envelope():
    fixture = FIXTURES["list_envelope"]
    result, err = await _drive_client(fixture)
    assert err is None
    expect = fixture["client_expect"]
    assert result["metadata"]["resourceVersion"] == expect["list_rv"]
    assert len(result["items"]) == expect["items_len"]
    assert result["kind"].endswith("List")


@pytest.mark.asyncio
@pytest.mark.parametrize("chunking", ["line", "single", "split"])
async def test_client_frames_recorded_watch_stream(chunking):
    """NDJSON framing must be independent of TCP chunk boundaries, and
    BOOKMARK events (metadata-only objects) must pass through with
    their resume resourceVersion intact."""
    fixture = FIXTURES["watch_stream"]
    events, err = await _drive_client(fixture, chunking)
    assert err is None
    expect = fixture["client_expect"]
    assert [e["type"] for e in events] == expect["event_types"]
    bookmark = events[-1]
    assert (
        bookmark["object"]["metadata"]["resourceVersion"]
        == expect["bookmark_rv"]
    )


@pytest.mark.asyncio
async def test_client_raises_on_recorded_watch_expiry():
    events, err = await _drive_client(FIXTURES["watch_expired"])
    assert events == []
    _check_error(FIXTURES["watch_expired"]["client_expect"], err)


@pytest.mark.asyncio
async def test_authorizer_against_recorded_review_responses():
    """KubeScrapeAuthorizer end-to-end against the RECORDED TokenReview
    and SubjectAccessReview bodies a real apiserver returns."""
    from activemonitor_tpu.kube.authn import KubeScrapeAuthorizer

    class BothReviews(ReplayServer):
        async def _handle(self, request):
            from aiohttp import web

            name = (
                "tokenreview"
                if "tokenreviews" in request.path
                else "subjectaccessreview"
            )
            recorded = FIXTURES[name]["response"]
            return web.json_response(recorded["body"], status=recorded["status"])

    server = BothReviews(FIXTURES["tokenreview"])
    await server.start()
    api = KubeApi(KubeConfig(server=server.url))
    try:
        auth = KubeScrapeAuthorizer(api)
        assert await auth.allowed("<redacted-sa-token>") is True
    finally:
        await api.close()
        await server.stop()


# -- stub conformance ---------------------------------------------------


def _status_shape_invariants(body: dict, invariants: dict):
    """The live stub response must carry the real apiserver shape the
    fixture records — keys AND the discriminating reason."""
    assert body.get("kind") == "Status"
    assert body.get("apiVersion") == "v1"
    assert "metadata" in body
    for key, want in invariants.items():
        assert body.get(key) == want, f"stub {key}={body.get(key)!r}, real {want!r}"
    if body.get("status") == "Failure":
        assert body.get("message")


async def _stub_scenario(scenario: str, invariants: dict):
    token = "secret" if scenario == "bad_token" else ""
    server = StubApiServer(token=token)
    await server.start()
    api = KubeApi(
        KubeConfig(
            server=server.url,
            token="wrong" if scenario == "bad_token" else token,
        )
    )
    path = "/apis/activemonitor.keikoproj.io/v1alpha1/namespaces/health/healthchecks"
    obj = {
        "apiVersion": "activemonitor.keikoproj.io/v1alpha1",
        "kind": "HealthCheck",
        "metadata": {"name": "demo", "namespace": "health"},
        "spec": {"repeatAfterSec": 60},
    }
    try:
        if scenario == "get_missing":
            with pytest.raises(ApiError) as exc:
                await api.get(f"{path}/demo")
        elif scenario == "bad_token":
            with pytest.raises(ApiError) as exc:
                await api.get(f"{path}/demo")
        elif scenario == "create_duplicate":
            await api.create(path, obj)
            with pytest.raises(ApiError) as exc:
                await api.create(path, obj)
        elif scenario == "replace_stale_rv":
            created = await api.create(path, obj)
            await api.merge_patch(f"{path}/demo", {"spec": {"repeatAfterSec": 30}})
            stale = dict(obj, metadata=dict(obj["metadata"]))
            stale["metadata"]["resourceVersion"] = created["metadata"][
                "resourceVersion"
            ]
            with pytest.raises(ApiError) as exc:
                await api.replace(f"{path}/demo", stale)
        elif scenario == "delete_existing":
            await api.create(path, obj)
            body = await api.delete(f"{path}/demo")
            return body
        elif scenario == "watch_ancient_rv":
            await api.create(path, obj)
            for sec in (10, 20, 30):
                await api.merge_patch(
                    f"{path}/demo", {"spec": {"repeatAfterSec": sec}}
                )
            # simulate the watch cache window moving past rv 1
            server._history = server._history[-1:]
            with pytest.raises(ApiError) as exc:
                async for _ in api.watch(path, resource_version="1"):
                    pass
        else:  # pragma: no cover - fixture/scenario drift guard
            raise AssertionError(f"unknown stub scenario {scenario}")
        err = exc.value
        assert err.status == invariants["code"]
        assert isinstance(err.body, dict)
        return err.body
    finally:
        await api.close()
        await server.stop()


@pytest.mark.asyncio
@pytest.mark.parametrize(
    "name", [n for n, f in FIXTURES.items() if "stub" in f]
)
async def test_stub_matches_recorded_shape(name):
    fixture = FIXTURES[name]
    body = await _stub_scenario(
        fixture["stub"]["scenario"], fixture["stub"].get("invariants", {})
    )
    _status_shape_invariants(body, fixture["stub"].get("invariants", {}))


@pytest.mark.asyncio
async def test_stub_watch_expiry_event_shape():
    """The stub's 410 travels as a watch ERROR event whose object is a
    full Status — same framing the watch_expired fixture records."""
    server = StubApiServer()
    await server.start()
    api = KubeApi(KubeConfig(server=server.url))
    path = "/apis/activemonitor.keikoproj.io/v1alpha1/namespaces/health/healthchecks"
    try:
        await api.create(
            path,
            {
                "apiVersion": "activemonitor.keikoproj.io/v1alpha1",
                "kind": "HealthCheck",
                "metadata": {"name": "demo", "namespace": "health"},
            },
        )
        for sec in (10, 20, 30):
            await api.merge_patch(f"{path}/demo", {"spec": {"repeatAfterSec": sec}})
        server._history = server._history[-1:]
        # read the raw stream to inspect the event envelope itself
        session = await api._ensure_session()
        async with session.get(
            api._url(path),
            params={"watch": "true", "resourceVersion": "1"},
            headers=await api._headers(),
        ) as resp:
            line = await resp.content.readline()
        event = json.loads(line)
        assert event["type"] == "ERROR"
        _status_shape_invariants(
            event["object"], {"code": 410, "reason": "Expired"}
        )
    finally:
        await api.close()
        await server.stop()


@pytest.mark.asyncio
async def test_stub_422_byte_equal_to_fixture():
    """With the generated CRD schema installed, the stub's live 422
    must equal the recorded wire bytes field for field — message
    aggregation, reason, AND details.causes (the invalid_422 fixture's
    stub column was previously unproven; see docs/conformance.md)."""
    from activemonitor_tpu.api.crd import build_crd

    fixture = FIXTURES["invalid_422"]
    server = StubApiServer()
    server.register_crd(build_crd())
    await server.start()
    api = KubeApi(KubeConfig(server=server.url))
    try:
        with pytest.raises(ApiError) as exc:
            await api.create(
                fixture["request"]["path"], fixture["request"]["body"]
            )
        assert exc.value.status == 422
        assert exc.value.body == fixture["response"]["body"]
    finally:
        await api.close()
        await server.stop()


@pytest.mark.asyncio
async def test_stub_422_on_merge_patch_result():
    """Validation runs on the post-merge object: a patch that flips a
    valid field to the wrong type is rejected, nothing stored."""
    from activemonitor_tpu.api.crd import build_crd

    server = StubApiServer()
    server.register_crd(build_crd())
    await server.start()
    api = KubeApi(KubeConfig(server=server.url))
    path = "/apis/activemonitor.keikoproj.io/v1alpha1/namespaces/health/healthchecks"
    try:
        await api.create(
            path,
            {
                "apiVersion": "activemonitor.keikoproj.io/v1alpha1",
                "kind": "HealthCheck",
                "metadata": {"name": "demo", "namespace": "health"},
                "spec": {"repeatAfterSec": 60},
            },
        )
        with pytest.raises(ApiError) as exc:
            await api.merge_patch(
                f"{path}/demo", {"spec": {"repeatAfterSec": "bad"}}
            )
        assert exc.value.status == 422
        causes = (exc.value.body.get("details") or {}).get("causes") or []
        assert causes and causes[0]["field"] == "spec.repeatAfterSec"
        stored = server.obj(
            "activemonitor.keikoproj.io",
            "v1alpha1",
            "healthchecks",
            "health",
            "demo",
        )
        assert stored["spec"]["repeatAfterSec"] == 60  # patch not stored
    finally:
        await api.close()
        await server.stop()


@pytest.mark.asyncio
async def test_stub_emits_interval_bookmarks():
    """A watch with allowWatchBookmarks=true receives metadata-only
    BOOKMARK events on the configured cadence, shaped like the
    watch_stream fixture's BOOKMARK entry."""
    import asyncio as aio

    server = StubApiServer()
    server.bookmark_interval = 0.05
    await server.start()
    api = KubeApi(KubeConfig(server=server.url))
    path = "/apis/activemonitor.keikoproj.io/v1alpha1/namespaces/health/healthchecks"
    try:
        await api.create(
            path,
            {
                "apiVersion": "activemonitor.keikoproj.io/v1alpha1",
                "kind": "HealthCheck",
                "metadata": {"name": "demo", "namespace": "health"},
                "spec": {"repeatAfterSec": 60},
            },
        )

        async def first_bookmark():
            async for event in api.watch(path):
                if event["type"] == "BOOKMARK":
                    return event

        event = await aio.wait_for(first_bookmark(), timeout=5.0)
        obj = event["object"]
        assert obj["kind"] == "HealthCheck"
        assert obj["apiVersion"] == "activemonitor.keikoproj.io/v1alpha1"
        # metadata-only: the resume RV and nothing object-specific
        assert obj["metadata"]["resourceVersion"] == str(server._rv)
        assert "name" not in obj["metadata"]
        assert "spec" not in obj
    finally:
        await api.close()
        await server.stop()


@pytest.mark.asyncio
async def test_client_bookmark_resume_end_to_end():
    """The controller watch's resume path, against a live server: a
    BOOKMARK advances the client's resume RV past the last real event,
    and the reconnect after a dropped stream carries the bookmark's RV
    (previously only replay-proven; the stub never sent bookmarks)."""
    import asyncio as aio

    from activemonitor_tpu.controller.client_k8s import (
        KubernetesHealthCheckClient,
    )

    server = StubApiServer()
    await server.start()  # interval off (60 s); emit_bookmarks() drives
    api = KubeApi(KubeConfig(server=server.url))
    path = "/apis/activemonitor.keikoproj.io/v1alpha1/namespaces/health/healthchecks"

    def hc(name):
        return {
            "apiVersion": "activemonitor.keikoproj.io/v1alpha1",
            "kind": "HealthCheck",
            "metadata": {"name": name, "namespace": "health"},
            "spec": {"repeatAfterSec": 60},
        }

    try:
        client = KubernetesHealthCheckClient(api)
        gen = client.watch()
        await api.create(path, hc("first"))
        event = await aio.wait_for(gen.__anext__(), timeout=5.0)
        assert (event.type, event.name) == ("ADDED", "first")
        # advance the global RV past the last HealthCheck event, then
        # bookmark: the client's resume point moves WITHOUT a real event
        await api.create(
            "/api/v1/namespaces/health/configmaps",
            {"kind": "ConfigMap", "metadata": {"name": "noise"}},
        )
        bookmark_rv = str(server._rv)
        assert server.emit_bookmarks() == 1
        await aio.sleep(0.1)  # let the bookmark reach the client
        server.drop_watches()
        await api.create(path, hc("second"))
        event = await aio.wait_for(gen.__anext__(), timeout=5.0)
        assert (event.type, event.name) == ("ADDED", "second")
        resumed = [p for p in server.watch_params if "resourceVersion" in p]
        assert resumed and resumed[-1]["resourceVersion"] == bookmark_rv
        await gen.aclose()
    finally:
        await api.close()
        await server.stop()
