"""Artifact store — pluggable sources for workflow manifests.

Capability match of the reference store (reference: internal/store/store.go:10-22)
plus a real file reader (the reference declares the File field but never
implements it — SURVEY.md §2 #12).
"""

from activemonitor_tpu.store.base import (
    ArtifactReader,
    UnknownArtifactLocation,
    get_artifact_reader,
    is_blocking_source,
)
from activemonitor_tpu.store.inline import InlineReader
from activemonitor_tpu.store.file import FileReader
from activemonitor_tpu.store.url import URLReader

__all__ = [
    "ArtifactReader",
    "FileReader",
    "InlineReader",
    "URLReader",
    "UnknownArtifactLocation",
    "get_artifact_reader",
    "is_blocking_source",
]
