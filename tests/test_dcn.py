"""Multi-host (DCN) probe tests — real multi-process collectives over
localhost Gloo, the CI stand-in for a multi-host TPU slice."""

import json
import os
import subprocess
import sys


from activemonitor_tpu.probes import dcn


def test_single_process_degrades_gracefully():
    result = dcn.run()
    assert result.ok
    assert result.details["processes"] == 1
    assert result.metrics[0].name == "dcn-hosts"


def test_two_process_dcn_allreduce():
    """Spawn two real worker processes; both run the dcn-allreduce probe
    CLI against a localhost coordinator and must agree + succeed."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 local device per process keeps it fast
    # pick a free port so concurrent/parallel test runs don't collide
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    workers = []
    for rank in range(2):
        workers.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    # config API beats the env-registered tunnel plugin
                    "import jax; jax.config.update('jax_platforms', 'cpu');"
                    "from activemonitor_tpu.probes.cli import main; import sys;"
                    "sys.exit(main(["
                    f"'--coordinator', '127.0.0.1:{port}',"
                    f"'--num-processes', '2', '--process-id', '{rank}',"
                    "'dcn-allreduce', '--size-mb', '1', '--iters', '2']))",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
        )
    outputs = []
    for proc in workers:
        out, _ = proc.communicate(timeout=150)
        outputs.append(out.decode())
        assert proc.returncode == 0, out.decode()[-1500:]
    for out in outputs:
        contract = json.loads(out.strip().splitlines()[-1])
        by_name = {m["name"]: m["value"] for m in contract["metrics"]}
        assert by_name["dcn-hosts"] == 2
        assert by_name["dcn-allreduce-correct"] == 1.0
        assert by_name["dcn-allreduce-busbw-gbps"] > 0
