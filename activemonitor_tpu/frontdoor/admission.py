"""Per-tenant admission control for the probe-as-a-service front door.

ROADMAP item 3: millions of users, one fleet. The apiserver-watch path
has no admission story — every CR is reconciled — but an open ingestion
surface needs one before anything else: a single hot tenant must not be
able to starve the fleet's measurement capacity. The quota primitive is
the existing :class:`~activemonitor_tpu.resilience.storm.TokenBucket`
(the fleet-wide remedy cap's bucket, reused per tenant), and routing is
the existing :class:`~activemonitor_tpu.controller.sharding.
ShardRouter` — a front-door request for check X lands on the SAME shard
the watch path would route X's reconcile to, so the sharded fleet's
ownership math applies unchanged to front-door traffic.

Refusals are STRUCTURED, never exceptions: a refusal names its tenant
and reason (``quota`` / ``unknown_tenant`` / ``parked_full``) and is
counted, because the per-tenant conservation ledger
(frontdoor/service.py) must account for every submitted request
exactly — a raised refusal would vanish from the books.

Everything here runs on the injectable Clock; ``hack/lint.py`` bans
bare wall-clock reads in the ``frontdoor`` package like resilience/
and analysis/.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from activemonitor_tpu.controller.sharding import ShardRouter
from activemonitor_tpu.resilience.storm import TokenBucket
from activemonitor_tpu.utils.clock import Clock

# refusal reasons (the structured vocabulary the refusal counters and
# the healthcheck_frontdoor_refusals_total{reason} label carry).
# quota/unknown_tenant/tenant_capacity refuse BEFORE admission;
# parked_full/abandoned/unrouted are post-admission outcomes the
# conservation ledger books separately
REFUSE_QUOTA = "quota"
REFUSE_UNKNOWN_TENANT = "unknown_tenant"
REFUSE_TENANT_CAPACITY = "tenant_capacity"  # max_tenants reached
REFUSE_PARKED_FULL = "parked_full"
REFUSE_ABANDONED = "abandoned"  # parked waiter cancelled before the pump
REFUSE_UNROUTED = "unrouted"  # sharded fleet: another replica owns the key

# the reasons refused before the tenant's bucket admitted the request
PRE_ADMISSION_REASONS = (
    REFUSE_QUOTA,
    REFUSE_UNKNOWN_TENANT,
    REFUSE_TENANT_CAPACITY,
)

# the ledger row never-seen tenants' refusals are booked under: the
# front door faces an open endpoint, so per-tenant state (buckets,
# tallies, refusal rows, metric series) must stay bounded by the
# admission config — a stranger spraying random tenant names mints ONE
# shared row, not one per name
OVERFLOW_TENANT = "(overflow)"

# default bound on lazily-minted tenant buckets (named quotas are
# config, bounded by definition; this caps the default-quota fleet)
DEFAULT_MAX_TENANTS = 1024

# tenant priority classes: ``low``-priority tenants are the shed class
# under adaptive degraded mode (resilience/adapt.py re-prices their
# quotas before the breaker has to trip); everyone else is ``normal``
PRIORITY_NORMAL = "normal"
PRIORITY_LOW = "low"


@dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission budget: requests/minute with a burst
    ceiling (defaults to the rate, the TokenBucket convention), plus a
    priority class (``low`` marks the tenant sheddable under adaptive
    degraded mode)."""

    rate_per_minute: float
    burst: Optional[float] = None
    priority: str = PRIORITY_NORMAL

    def bucket(self, clock: Clock) -> TokenBucket:
        return TokenBucket(self.rate_per_minute, burst=self.burst, clock=clock)


@dataclass(frozen=True)
class AdmissionDecision:
    """The structured admit/refuse verdict for one request.

    ``tenant`` is the caller's spelling (echoed in the reply);
    ``booked`` is the ledger row the decision was accounted under —
    identical for every known tenant, ``(overflow)`` for never-seen
    names refused without minting per-name state.
    """

    admitted: bool
    tenant: str
    shard: int  # ShardRouter assignment of the check key (0 unsharded)
    reason: str = ""  # refusal vocabulary above; "" when admitted
    booked: str = ""  # ledger row (defaults to tenant in __post_init__)

    def __post_init__(self):
        if not self.booked:
            object.__setattr__(self, "booked", self.tenant)


class AdmissionController:
    """Per-tenant token buckets + shard routing, with refusals counted.

    ``quotas`` names the known tenants; ``default_quota`` (optional)
    admits tenants that were never configured — omit it and an unknown
    tenant is a structured ``unknown_tenant`` refusal (a closed fleet),
    set it and new tenants get the default budget lazily (an open
    fleet). Buckets are created on first use so a million-tenant fleet
    pays memory only for tenants that actually talk.
    """

    def __init__(
        self,
        quotas: Optional[Dict[str, TenantQuota]] = None,
        *,
        default_quota: Optional[TenantQuota] = None,
        router: Optional[ShardRouter] = None,
        clock: Optional[Clock] = None,
        max_tenants: int = DEFAULT_MAX_TENANTS,
    ):
        self.clock = clock or Clock()
        self._quotas = dict(quotas or {})
        self._default = default_quota
        self._router = router
        self.max_tenants = max(1, int(max_tenants))
        self._buckets: Dict[str, TokenBucket] = {}
        # quota each live bucket was minted from, so degraded-mode
        # re-pricing (shed_low_priority) can find the low-priority
        # buckets and restore_quotas can return them to configured rate
        self._bucket_quota: Dict[str, TenantQuota] = {}
        # active shed factor (None = normal mode); applied to already-
        # minted low-priority buckets at engage time and to any minted
        # while degraded
        self.shed_factor: Optional[float] = None
        # per-tenant ledger: admitted counts and refusals by reason —
        # the raw material of the conservation property test. Keyed by
        # the BOOKED name (never-seen tenants' refusals share the
        # (overflow) row), so the endpoint cannot mint unbounded state
        self.admitted: Dict[str, int] = {}
        self.refused: Dict[str, Dict[str, int]] = {}

    def shard_for(self, key: str) -> int:
        """The check key's shard under the fleet's router (0 when the
        front door serves an unsharded fleet)."""
        return self._router.shard_for(key) if self._router is not None else 0

    def _resolve(self, tenant: str) -> tuple:
        """(bucket|None, refusal-reason|None): an existing bucket or
        named quota always resolves; a default-quota tenant mints a
        bucket only under the ``max_tenants`` cap (beyond it the
        refusal books under the shared overflow row); no default means
        a closed fleet."""
        bucket = self._buckets.get(tenant)
        if bucket is not None:
            return bucket, None
        quota = self._quotas.get(tenant)
        if quota is None:
            if self._default is None:
                return None, REFUSE_UNKNOWN_TENANT
            if len(self._buckets) >= self.max_tenants:
                return None, REFUSE_TENANT_CAPACITY
            quota = self._default
        bucket = self._buckets[tenant] = quota.bucket(self.clock)
        self._bucket_quota[tenant] = quota
        if self.shed_factor is not None and quota.priority == PRIORITY_LOW:
            bucket.set_rate(quota.rate_per_minute * self.shed_factor)
        return bucket, None

    # -- degraded-mode quota re-pricing (resilience/adapt.py) -----------
    def shed_low_priority(self, factor: float) -> int:
        """Re-price every low-priority tenant's bucket to ``factor`` of
        its configured rate (and apply the same to buckets minted while
        degraded). Sheds are ordinary structured ``quota`` refusals —
        the conservation ledger needs no new vocabulary, and normal-
        priority tenants are untouched. Returns how many live buckets
        were re-priced."""
        self.shed_factor = max(0.01, min(1.0, float(factor)))
        repriced = 0
        for tenant, bucket in self._buckets.items():
            quota = self._bucket_quota.get(tenant)
            if quota is not None and quota.priority == PRIORITY_LOW:
                bucket.set_rate(quota.rate_per_minute * self.shed_factor)
                repriced += 1
        return repriced

    def restore_quotas(self) -> int:
        """Release degraded mode: every re-priced bucket returns to its
        configured rate (settled in place — no fresh burst is granted,
        the :meth:`TokenBucket.set_rate` contract). Returns how many
        buckets were restored."""
        if self.shed_factor is None:
            return 0
        self.shed_factor = None
        restored = 0
        for tenant, bucket in self._buckets.items():
            quota = self._bucket_quota.get(tenant)
            if quota is not None and quota.priority == PRIORITY_LOW:
                bucket.set_rate(quota.rate_per_minute)
                restored += 1
        return restored

    def refuse(
        self, tenant: str, reason: str, booked: Optional[str] = None
    ) -> AdmissionDecision:
        """Count and return a structured refusal (also used by the
        service for post-admission refusals like a full parking lot, so
        every refusal path shares one ledger). ``booked`` overrides the
        ledger row — never-seen tenants share ``(overflow)`` so random
        names cannot mint unbounded rows or metric series."""
        row = booked if booked is not None else tenant
        per_tenant = self.refused.setdefault(row, {})
        per_tenant[reason] = per_tenant.get(reason, 0) + 1
        return AdmissionDecision(
            admitted=False, tenant=tenant, shard=0, reason=reason, booked=row
        )

    def admit(self, tenant: str, key: str) -> AdmissionDecision:
        """One request's admission verdict: unknown tenants refuse
        (closed fleet), a tenant beyond the lazily-minted bucket cap
        refuses ``tenant_capacity`` (booked under the overflow row),
        then the tenant's bucket pays one token or the request refuses
        with ``quota``. Admissions and refusals both land in the
        per-tenant ledger."""
        bucket, reason = self._resolve(tenant)
        if bucket is None:
            return self.refuse(tenant, reason, booked=OVERFLOW_TENANT)
        if not bucket.try_take():
            return self.refuse(tenant, REFUSE_QUOTA)
        self.admitted[tenant] = self.admitted.get(tenant, 0) + 1
        return AdmissionDecision(
            admitted=True, tenant=tenant, shard=self.shard_for(key)
        )

    def snapshot(self) -> dict:
        """The admission half of the front door's /statusz block."""
        tenants = sorted(set(self.admitted) | set(self.refused))
        return {
            "tenants": {
                tenant: {
                    "admitted": self.admitted.get(tenant, 0),
                    "refused": dict(self.refused.get(tenant, {})),
                }
                for tenant in tenants
            },
        }
