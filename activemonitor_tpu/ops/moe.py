"""Expert parallelism — a mixture-of-experts FFN sharded over "ep".

Experts live sharded across the mesh (E/n per device); tokens are
sharded over the same axis (the usual ep≡dp setup). Each round:
``all_gather`` the token shards so every device sees all tokens, each
device runs only ITS experts on the tokens routed to them (top-1
learned router, softmax gate), and ``psum_scatter`` returns each
token's single expert output to the device that owns the token — the
all_gather/reduce-scatter pair is the collective skeleton of MoE
dispatch/combine.

This formulation computes each local expert over the full token set and
masks (dense dispatch) — exactly correct, static-shaped, and the right
fidelity for a *health probe* of expert-parallel collectives; a
production MoE would add capacity-based gather/scatter to skip the
masked compute.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from activemonitor_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def init_moe_params(
    key: jax.Array, d_model: int, d_ff: int, n_experts: int
) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / jnp.sqrt(d_model)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts), jnp.float32) * scale,
        "w_up": jax.random.normal(k2, (n_experts, d_model, d_ff), jnp.float32) * scale,
        "w_down": jax.random.normal(k3, (n_experts, d_ff, d_model), jnp.float32)
        * (1.0 / jnp.sqrt(d_ff)),
    }


def moe_ffn_reference(params: Dict, x: jax.Array) -> jax.Array:
    """Single-device dense MoE (top-1): the correctness oracle."""
    logits = x @ params["router"]  # [T, E]
    expert = jnp.argmax(logits, axis=-1)  # [T]
    gate = jax.nn.softmax(logits, axis=-1)
    gate = jnp.take_along_axis(gate, expert[:, None], axis=-1)  # [T, 1]
    h = jnp.einsum("td,edf->tef", x, params["w_up"])
    h = jax.nn.gelu(h)
    y = jnp.einsum("tef,efd->ted", h, params["w_down"])  # [T, E, D]
    chosen = jnp.take_along_axis(y, expert[:, None, None], axis=1)[:, 0]
    return chosen * gate


def moe_ffn_expert_parallel(
    params: Dict, x: jax.Array, mesh: Mesh, axis: str = "ep"
) -> jax.Array:
    """x: [T, D] with T sharded over ``mesh[axis]``; experts sharded the
    same way. Returns [T, D] sharded like x."""
    n = mesh.shape[axis]
    n_experts = params["router"].shape[1]
    if n_experts % n:
        raise ValueError(f"{n_experts} experts do not split over {n} devices")
    if x.shape[0] % n:
        raise ValueError(f"{x.shape[0]} tokens do not shard over {n} devices")
    e_local = n_experts // n

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, None), P(axis, None, None), P(axis, None, None), P(axis, None)),
        out_specs=P(axis, None),
        check_vma=False,
    )
    def run(router, w_up, w_down, x_shard):
        my_rank = jax.lax.axis_index(axis)
        tokens = jax.lax.all_gather(x_shard, axis, tiled=True)  # [T, D]
        logits = tokens @ router
        expert = jnp.argmax(logits, axis=-1)
        gate = jax.nn.softmax(logits, axis=-1)
        gate = jnp.take_along_axis(gate, expert[:, None], axis=-1)  # [T, 1]
        out = jnp.zeros_like(tokens)
        for e in range(e_local):  # static loop over this device's experts
            eid = my_rank * e_local + e
            mask = (expert == eid)[:, None].astype(tokens.dtype)
            h = jax.nn.gelu(tokens @ w_up[e])
            out = out + mask * gate * (h @ w_down[e])
        # each token's output exists on exactly one device: the scatter-sum
        # both combines and re-shards back to the token owners
        return jax.lax.psum_scatter(out, axis, scatter_dimension=0, tiled=True)

    return run(params["router"], params["w_up"], params["w_down"], x)
