"""TTL-cached token file.

Secrets mounted into pods rotate (bound SA tokens ~1h, scrape tokens on
operator action); anything comparing or sending such a token must
re-read the file periodically instead of snapshotting it at startup.
One implementation, shared by the metrics auth filter and the cluster
credentials (kube.config).
"""

from __future__ import annotations

import logging
import time

log = logging.getLogger("activemonitor.tokenfile")

DEFAULT_TTL = 60.0


class FileToken:
    """A token string, re-read from ``path`` at most every ``ttl``
    seconds. With no path it is just a static value.

    ``on_error`` picks the failure policy — the two consumers genuinely
    differ: ``"keep"`` (default) holds the last good value, right for
    CLIENT credentials where a transient kubelet-rotation glitch must
    not drop cluster auth; ``"clear"`` empties the value, right for
    SERVER-side auth where a deleted/unmounted token file means the
    operator revoked access and the gate must fail closed."""

    def __init__(
        self,
        path: str = "",
        initial: str = "",
        ttl: float = DEFAULT_TTL,
        on_error: str = "keep",
    ):
        if on_error not in ("keep", "clear"):
            # a typo silently meaning fail-open would defeat the very
            # policy this parameter selects
            raise ValueError(f"on_error must be 'keep' or 'clear', got {on_error!r}")
        self.path = path
        self._value = initial
        self._ttl = ttl
        self._on_error = on_error
        # -inf, not 0.0: monotonic() starts near zero after host boot,
        # and "never read" must always trigger the first read
        self._read_at = float("-inf")

    def get(self) -> str:
        if self.path and time.monotonic() - self._read_at > self._ttl:
            try:
                with open(self.path) as f:
                    self._value = f.read().strip()
            except OSError:
                if self._on_error == "clear":
                    log.warning(
                        "token file %s unreadable; clearing value (fail closed)",
                        self.path,
                    )
                    self._value = ""
                else:
                    log.warning(
                        "token file %s unreadable; keeping previous value", self.path
                    )
            self._read_at = time.monotonic()
        return self._value

    def expire(self) -> None:
        """Force the next get() to re-read (tests)."""
        self._read_at = float("-inf")
