"""Benchmark entry point — prints ONE JSON line.

Adaptive to the hardware it lands on (BASELINE.md):

- multi-chip TPU: the north-star ICI all-reduce probe — fraction of
  rated ring bandwidth (target ≥ 0.9).
- single-chip TPU: the MXU matmul probe — fraction of rated bf16 peak
  (the per-chip floor under every distributed target) — plus secondary
  metrics for the kernel work (flash-attention fwd and fwd+bwd
  TFLOP/s, HBM stream fraction, int8 fraction) so perf claims are
  driver-evidenced, not comment-lore.
- CPU (virtual mesh): informational all-reduce GB/s.

``vs_baseline`` is measured / target-fraction (0.9): ≥1.0 beats the
BASELINE.md bar. All timing uses the chain-difference method so tunnel
and dispatch overhead cancel (utils/timing.py).

Resilience: the device tunnel can wedge (observed: jax.devices() hangs
forever), usually transiently. Reachability is probed in a killable
subprocess with RETRIES spread over ~10 minutes, and the real TPU
measurement itself runs in a killable subprocess under a deadline — a
wedge at any point degrades to the CPU-mesh fallback with the real
diagnostic instead of hanging the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_PROBE_TIMEOUT = float(os.environ.get("ACTIVEMONITOR_BENCH_PROBE_TIMEOUT", "120"))
_PROBE_ATTEMPTS = int(os.environ.get("ACTIVEMONITOR_BENCH_PROBE_ATTEMPTS", "4"))
# deadline for the full TPU measurement pass (compiles included)
_MEASURE_TIMEOUT = float(os.environ.get("ACTIVEMONITOR_BENCH_MEASURE_TIMEOUT", "1800"))
_TARGET_FRACTION = 0.9

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "print(float(jax.jit(lambda a:(a@a).astype(jnp.float32).sum())"
    "(jnp.ones((128,128), jnp.bfloat16))))"
)


# consecutive hangs after which the probe gives up early: r02–r05 all
# wedged for entire rounds — once two full timeouts hang back-to-back
# the tunnel is not transiently blipping, and burning the remaining
# retry window only delays the (inevitable) CPU fallback
_PROBE_HANG_FAIL_FAST = 2


def _device_reachable() -> tuple:
    """Probe the device in a killable subprocess, retrying across a
    ~10-minute window: tunnel wedges are transient (BENCH_r02 lost its
    TPU artifact to a single 180s attempt that would have succeeded
    minutes later). Returns ``(reachable, reason)`` — the reason string
    lands in the artifact as ``fallback_reason`` so degraded rounds
    (r02–r05 fell back with zero recorded cause) say WHY on the JSON
    line, not just in scrollback. Two consecutive hangs fail fast: a
    tunnel that ate two full timeouts is wedged, not blipping."""
    reason = ""
    consecutive_hangs = 0
    for attempt in range(1, _PROBE_ATTEMPTS + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                timeout=_PROBE_TIMEOUT,
                capture_output=True,
            )
        except subprocess.TimeoutExpired:
            consecutive_hangs += 1
            reason = (
                f"device probe hung past {_PROBE_TIMEOUT:.0f}s on attempt "
                f"{attempt}/{_PROBE_ATTEMPTS} (wedged tunnel?)"
            )
            print(reason, file=sys.stderr)
            if consecutive_hangs >= _PROBE_HANG_FAIL_FAST:
                reason += (
                    f"; {consecutive_hangs} consecutive hangs — failing fast"
                )
                print(
                    f"{consecutive_hangs} consecutive probe hangs; failing "
                    "fast to the CPU fallback",
                    file=sys.stderr,
                )
                return False, reason
        else:
            if proc.returncode == 0:
                return True, ""
            consecutive_hangs = 0
            # surface the real diagnostic (libtpu init error, plugin
            # mismatch, OOM) instead of a misleading timeout claim
            tail = proc.stderr.decode(errors="replace").strip().splitlines()[-8:]
            reason = (
                f"device probe exited with {proc.returncode} on attempt "
                f"{attempt}/{_PROBE_ATTEMPTS}: " + " | ".join(tail[-2:])
            )
            print(
                f"device probe attempt {attempt}/{_PROBE_ATTEMPTS} exited with "
                f"{proc.returncode}:\n" + "\n".join(tail),
                file=sys.stderr,
            )
        if attempt < _PROBE_ATTEMPTS:
            delay = 30.0 * attempt  # 30/60/90s between 4 attempts ≈ 11 min worst case
            print(f"retrying device probe in {delay:.0f}s", file=sys.stderr)
            time.sleep(delay)
    return False, reason or "device probe exhausted every attempt"


def _force_cpu_mesh() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def _secondary_metrics() -> dict:
    """Kernel/memory-path numbers measured alongside the primary on a
    real chip. Each is individually guarded: one failing probe costs
    that entry, not the bench artifact."""
    secondary: dict = {}

    def guarded(name, fn):
        try:
            fn()
        except Exception as exc:  # pragma: no cover - depends on hardware
            print(f"secondary metric {name} failed: {exc!r}", file=sys.stderr)
            secondary[f"{name}_error"] = str(exc)[:200]

    def flash():
        from activemonitor_tpu.probes import flash as flash_probe

        result = flash_probe.run(iters=3)
        by_name = {m.name: m.value for m in result.metrics}
        secondary["flash_attention_tflops"] = round(
            by_name["flash-attention-tflops"], 2
        )
        if "flash-attention-train-tflops" in by_name:
            secondary["flash_attention_train_tflops"] = round(
                by_name["flash-attention-train-tflops"], 2
            )
        if "flash-attention-fraction-of-rated" in by_name:
            secondary["flash_attention_fraction_of_rated"] = round(
                by_name["flash-attention-fraction-of-rated"], 4
            )
        if "flash-attention-speedup" in by_name:
            secondary["flash_attention_speedup_vs_xla"] = round(
                by_name["flash-attention-speedup"], 2
            )

    def hbm():
        from activemonitor_tpu.probes import hbm as hbm_probe

        result = hbm_probe.run(iters=5)
        by_name = {m.name: m.value for m in result.metrics}
        secondary["hbm_stream_gbps"] = round(by_name["hbm-stream-gbps"], 1)
        if "hbm-fraction-of-rated" in by_name:
            secondary["hbm_stream_fraction_of_rated"] = round(
                by_name["hbm-fraction-of-rated"], 4
            )

    def int8():
        from activemonitor_tpu.probes import matmul as matmul_probe

        result = matmul_probe.run(iters=5, dtype="int8")
        by_name = {m.name: m.value for m in result.metrics}
        secondary["mxu_int8_tops"] = round(by_name["mxu-int8-matmul-tops"], 1)
        if "mxu-int8-fraction-of-rated" in by_name:
            secondary["mxu_int8_fraction_of_rated"] = round(
                by_name["mxu-int8-fraction-of-rated"], 4
            )

    def train():
        from activemonitor_tpu.probes import training_step as train_probe

        result = train_probe.run(
            batch_per_device=8, seq=128, steps=3, tune_sync=True
        )
        by_name = {m.name: m.value for m in result.metrics}
        if "train-mfu" in by_name:
            # the measured value BASELINE.md's provisional TRAIN_MFU_BAR
            # waits on — captured to BENCH_TPU.json by the evidence harness
            secondary["train_mfu"] = round(by_name["train-mfu"], 4)
        secondary["train_tokens_per_second"] = round(
            by_name["train-tokens-per-second"]
        )
        # tuned-dispatch evidence: which schedule the gradient sync
        # rode (or why it stayed implicit), plus the measured
        # tuned-vs-builtin step-time speedup when a zoo schedule won
        secondary["train_allreduce_schedule"] = result.details.get(
            "allreduce_schedule", "xla(implicit)"
        )
        if "training-step-allreduce-sched" in by_name:
            secondary["train_allreduce_sched_speedup"] = round(
                by_name["training-step-allreduce-sched"], 3
            )

    def decode():
        from activemonitor_tpu.probes import decode as decode_probe

        result = decode_probe.run(
            batch=8, prompt_len=64, decode_tokens=128, iters=3, use_flash=True
        )
        by_name = {m.name: m.value for m in result.metrics}
        secondary["decode_fused_vs_dense_rel_diff"] = result.details[
            "flash_vs_dense_rel_diff"
        ]
        if not result.ok:
            # a throughput number must not outlive a failed correctness
            # gate — record the failure, not a clean-looking tokens/s
            secondary["decode_fused_error"] = result.summary[:200]
            return
        secondary["decode_fused_tokens_per_second"] = round(
            by_name["decode-tokens-per-second"]
        )

    def ring_overlap():
        import jax

        if len(jax.devices()) < 2:
            return  # no ring to rotate on one chip
        from activemonitor_tpu.probes import ring as ring_probe

        result = ring_probe.run(
            batch=1, seq_per_device=1024, heads=8, head_dim=128, iters=3
        )
        if not result.ok:
            # overlap throughput must not outlive a failed numerics gate
            # — record the failure, not clean-looking efficiency numbers
            secondary["ring_overlap_error"] = result.summary[:200]
            return
        by_name = {m.name: m.value for m in result.metrics}
        secondary["ring_overlap_efficiency"] = round(
            by_name["ring-overlap-efficiency"], 3
        )
        secondary["ring_attention_busbw_gbps"] = round(
            by_name["ring-attention-busbw-gbps"], 2
        )
        if "ring-attention-busbw-fraction-of-rated" in by_name:
            secondary["ring_busbw_fraction_of_rated"] = round(
                by_name["ring-attention-busbw-fraction-of-rated"], 4
            )

    guarded("flash_attention", flash)
    guarded("hbm_stream", hbm)
    guarded("mxu_int8", int8)
    guarded("training_step", train)
    guarded("decode_fused", decode)
    guarded("ring_overlap", ring_overlap)
    return secondary


def _cpu_secondary_metrics() -> dict:
    """Functional kernel evidence that survives a wedged tunnel: the
    fallback artifact must still show the round's kernels RUN (VERDICT
    r3 weak #1 — a degraded round previously produced zero evidence
    about kernel work). Interpret-mode correctness, not timing."""
    secondary: dict = {}
    try:
        import jax
        import jax.numpy as jnp

        from activemonitor_tpu.ops.flash_attention import flash_attention
        from activemonitor_tpu.ops.ring_attention import reference_attention

        keys = jax.random.split(jax.random.key(0), 3)
        q, k, v = (
            jax.random.normal(kk, (1, 128, 2, 64), jnp.bfloat16) for kk in keys
        )
        got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        want = reference_attention(q, k, v, causal=True)
        secondary["flash_fwd_max_error_interpret"] = round(
            float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))),
            6,
        )

        def loss(fn, *args):
            return jnp.sum(fn(*args).astype(jnp.float32) ** 2)

        g_flash = jax.grad(
            lambda a, b, c: loss(
                lambda *xs: flash_attention(*xs, causal=True, block_q=64, block_k=64),
                a, b, c,
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_ref = jax.grad(
            lambda a, b, c: loss(
                lambda *xs: reference_attention(*xs, causal=True), a, b, c
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        rel = 0.0
        for a, b in zip(g_flash, g_ref):
            norm = max(1e-9, float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
            rel = max(
                rel,
                float(
                    jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
                )
                / norm,
            )
        secondary["flash_grad_rel_error_interpret"] = round(rel, 6)
    except Exception as exc:  # pragma: no cover - defensive
        secondary["flash_interpret_error"] = str(exc)[:200]

    try:
        import jax
        import jax.numpy as jnp

        from activemonitor_tpu.models.probe_model import (
            ProbeModelConfig,
            decode_step,
            init_kv_cache,
            init_params,
        )

        cfg = ProbeModelConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
            d_ff=64, max_seq_len=16, dtype=jnp.float32,
        )
        params = init_params(jax.random.key(0), cfg)
        # several positions, so the fused online softmax actually sweeps
        # multiple visible keys — a pos=0 comparison is vacuous (both
        # paths return v_new when only one key is visible)
        tokens = jax.random.randint(jax.random.key(2), (2, 4), 0, cfg.vocab_size)
        cache_d = init_kv_cache(cfg, 2, 8)
        cache_f = init_kv_cache(cfg, 2, 8)
        for p in range(tokens.shape[1]):
            dense_logits, cache_d = decode_step(
                params, cache_d, tokens[:, p], jnp.int32(p), cfg
            )
            fused_logits, cache_f = decode_step(
                params, cache_f, tokens[:, p], jnp.int32(p), cfg, use_flash=True
            )
        secondary["decode_fused_vs_dense_interpret"] = round(
            float(jnp.max(jnp.abs(dense_logits - fused_logits))), 6
        )
    except Exception as exc:  # pragma: no cover - defensive
        secondary["decode_interpret_error"] = str(exc)[:200]

    try:
        import jax
        import jax.numpy as jnp

        if len(jax.devices()) >= 2:
            from activemonitor_tpu.ops.ring_attention import (
                reference_attention,
                ring_attention,
            )
            from activemonitor_tpu.parallel.mesh import make_1d_mesh

            mesh = make_1d_mesh("sp")
            n = mesh.devices.size
            keys = jax.random.split(jax.random.key(3), 3)
            rq, rk, rv = (
                jax.random.normal(kk, (1, 16 * n, 2, 16), jnp.float32)
                for kk in keys
            )
            ref = reference_attention(rq, rk, rv, causal=True)
            serial = ring_attention(rq, rk, rv, mesh, "sp", variant="serial")
            overlap = ring_attention(rq, rk, rv, mesh, "sp", variant="overlap")
            bidir = ring_attention(rq, rk, rv, mesh, "sp", variant="bidir")
            # overlapped schedule is a bit-compat contract vs serial;
            # bidir merges halves in a different order (tolerance vs ref)
            secondary["ring_overlap_vs_serial_max_error"] = float(
                jnp.max(jnp.abs(overlap - serial))
            )
            secondary["ring_bidir_max_error_interpret"] = round(
                float(jnp.max(jnp.abs(bidir - ref))), 6
            )
    except Exception as exc:  # pragma: no cover - defensive
        secondary["ring_overlap_interpret_error"] = str(exc)[:200]

    try:
        import jax
        import jax.numpy as jnp

        if len(jax.devices()) >= 8:
            from activemonitor_tpu.models.probe_model import tiny_config
            from activemonitor_tpu.parallel import autotune
            from activemonitor_tpu.parallel.mesh import make_mesh
            from activemonitor_tpu.probes.training_step import (
                build_composed_train_step,
            )

            mesh = make_mesh(
                ("data", "model", "pp"), (2, 2, 2), devices=jax.devices()[:8]
            )
            cfg = tiny_config()
            # tuned-dispatch evidence for the composed hot path: race
            # every all-reduce schedule on the pp axis at the pipeline
            # output-combine payload, then report the schedule the
            # composed step's autotune.all_reduce(schedule="auto")
            # resolves. Interpret-mode timings (labeled): table SHAPE,
            # never read against a TPU bar. Stamped before the step so
            # the evidence survives a legacy-gated composed mode.
            combine_payload = 2 * 2 * 16 * cfg.d_model * 4  # [M,mb,S,D] f32
            tuned = autotune.tune(
                mesh, axis="pp", collectives=("allreduce",),
                sizes_mb=(max(0.05, combine_payload / 1e6),),
                dtype=jnp.float32, iters=2,
            )
            cell = next(iter(tuned.results["allreduce"].values()))
            sched = (
                autotune.lookup(
                    "allreduce", mesh.shape["pp"], combine_payload, jnp.float32
                )
                or "xla"
            )
            secondary["composed_allreduce_schedule"] = sched
            if cell.get("xla", 0.0) > 0 and sched in cell:
                secondary["composed_allreduce_tuned_vs_builtin_interpret"] = (
                    round(cell[sched] / cell["xla"], 3)
                )
            step, params, opt, data_sh = build_composed_train_step(cfg, mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.key(7), (4, 17), 0, cfg.vocab_size),
                data_sh,
            )
            _, _, c_loss = step(params, opt, tokens)
            secondary["composed_dp_tp_pp_loss"] = round(float(c_loss), 4)
    except Exception as exc:  # pragma: no cover - defensive
        secondary["composed_step_error"] = str(exc)[:200]
    return secondary


def _last_known_good_tpu(path: str | None = None) -> dict | None:
    """Embed the opportunistic harness's capture (hack/tpu_evidence.py)
    so a wedged end-of-round artifact still carries real TPU numbers,
    clearly timestamped as an earlier measurement."""
    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU.json"
        )
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    block = {
        key: doc[key]
        for key in (
            "metric", "value", "unit", "vs_baseline", "platform",
            "n_devices", "device_kind", "secondary", "captured_at",
            # the real-TPU autotune table survives a wedged round the
            # same way the kernel numbers do
            "collective_autotune",
        )
        if key in doc
    }
    sweep = doc.get("flash_sweep", {})
    if isinstance(sweep, dict) and "summary" in sweep:
        block["flash_sweep_summary"] = sweep["summary"]
    block["source"] = "BENCH_TPU.json (hack/tpu_evidence.py mid-round capture)"
    return block or None


def _last_driver_captured_tpu() -> dict | None:
    """When no mid-round capture exists (the tunnel has wedged through
    entire rounds), fall back to the newest DRIVER-captured real-TPU
    bench from this repo's own history (BENCH_r*.json): honest, clearly
    sourced, and better context than nothing."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))

    def round_no(path: str) -> int:
        m = re.search(r"r(\d+)", os.path.basename(path))
        return int(m.group(1)) if m else -1

    # numeric, not lexicographic: 'r100' must outrank 'r99'
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       key=round_no, reverse=True):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = doc.get("parsed") or {}
        # a real-TPU datum never carries the CPU fallback marker — and
        # rounds 2-3 predate the "platform" key, so the metric NAME is
        # the reliable discriminator (the CPU fallback metric says so)
        if not parsed or parsed.get("platform") == "cpu":
            continue
        if "cpu" in str(parsed.get("metric", "")):
            continue
        if parsed.get("vs_baseline") is None:
            continue
        return {
            **{k: parsed[k] for k in ("metric", "value", "unit", "vs_baseline")
               if k in parsed},
            "source": f"{os.path.basename(path)} (driver-captured end-of-round)",
        }
    return None


def _prior_cpu_mesh_value() -> tuple | None:
    """Newest driver-captured CPU-mesh busbw from this repo's own
    BENCH_r*.json history — the denominator that keeps fallback rounds'
    trajectories comparable (the CPU line used to pin vs_baseline to
    null on EVERY fallback, so consecutive degraded rounds could not be
    compared at all). Returns (value, source_basename) or None."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))

    def round_no(path: str) -> int:
        m = re.search(r"r(\d+)", os.path.basename(path))
        return int(m.group(1)) if m else -1

    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       key=round_no, reverse=True):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = doc.get("parsed") or {}
        value = parsed.get("value")
        if (
            parsed.get("metric") == "allreduce_busbw_cpu_mesh"
            and isinstance(value, (int, float))
            and value > 0
        ):
            return float(value), os.path.basename(path)
    return None


def _measure(want_cpu: bool, fallback: bool = False, fallback_reason: str = "") -> dict:
    import jax

    if want_cpu:
        # site customizations (e.g. an accelerator plugin on PYTHONPATH)
        # can override the env var; the config API outranks them —
        # shared primitive, activemonitor_tpu/utils/platform.py. Fail
        # LOUD if the pin doesn't take: numbers measured on the remote
        # device must never be emitted labeled as the CPU fallback
        from activemonitor_tpu.utils.platform import force_cpu

        if not force_cpu():
            raise RuntimeError(
                "could not pin the CPU backend (already initialized on "
                "another platform) — refusing to mislabel measurements"
            )

    # persistent compile cache: the secondary probes re-run kernels the
    # battery already compiled on this chip
    try:
        from activemonitor_tpu.probes.suite import enable_persistent_compile_cache

        enable_persistent_compile_cache()
    except Exception as e:
        # cold-compile still works, just slower; say so off the JSON line
        print(f"compile cache unavailable: {e}", file=sys.stderr)

    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform

    primary_result = None
    if platform == "tpu" and n > 1:
        from activemonitor_tpu.probes import ici

        result = ici.run(size_mb=64, iters=5, threshold=_TARGET_FRACTION)
        primary_result = result
        by_name = {m.name: m.value for m in result.metrics}
        fraction = by_name.get("ici-allreduce-fraction-of-rated")
        if fraction is not None:
            doc = {
                "metric": "ici_allreduce_fraction_of_rated",
                "value": round(fraction, 4),
                "unit": "fraction",
                "vs_baseline": round(fraction / _TARGET_FRACTION, 4),
            }
        else:
            doc = {
                "metric": "ici_allreduce_busbw",
                "value": round(by_name["ici-allreduce-busbw-gbps"], 2),
                "unit": "GB/s",
                "vs_baseline": 1.0,
            }
        doc["secondary"] = _secondary_metrics()
    elif platform == "tpu":
        from activemonitor_tpu.probes import matmul

        # median-of-3: each run is already a max over a dim sweep of
        # min-sampled chain deltas; taking a further max would compound
        # the upward bias into physically impossible >1.0-of-rated
        # readings, while the median stays an honest estimate
        runs = []
        for _ in range(3):
            result = matmul.run(iters=5, threshold=_TARGET_FRACTION)
            runs.append((result, {m.name: m.value for m in result.metrics}))
        runs.sort(key=lambda r: r[1].get("mxu-matmul-tflops", 0))
        primary_result, by_name = runs[len(runs) // 2]
        fraction = by_name.get("mxu-fraction-of-rated")
        if fraction is not None:
            doc = {
                "metric": "mxu_bf16_fraction_of_rated",
                "value": round(fraction, 4),
                "unit": "fraction",
                "vs_baseline": round(fraction / _TARGET_FRACTION, 4),
            }
        else:
            doc = {
                "metric": "mxu_bf16_tflops",
                "value": round(by_name["mxu-matmul-tflops"], 2),
                "unit": "TFLOP/s",
                "vs_baseline": 1.0,
            }
        doc["secondary"] = _secondary_metrics()
    else:
        from activemonitor_tpu.probes import ici

        result = ici.run(size_mb=8, iters=3)
        primary_result = result
        by_name = {m.name: m.value for m in result.metrics}
        # a CPU number measures nothing against the TPU baseline — but
        # it CAN be compared against the previous CPU-mesh round, so
        # consecutive fallback rounds keep a trajectory. vs_baseline is
        # that CPU-vs-CPU ratio when a prior CPU artifact exists
        # (explicitly labeled via baseline_source so it can never read
        # as "meets the TPU bar", VERDICT r3 weak #1), null otherwise.
        doc = {
            "metric": "allreduce_busbw_cpu_mesh",
            "value": round(by_name["ici-allreduce-busbw-gbps"], 2),
            "unit": "GB/s",
            "vs_baseline": None,
            "secondary": _cpu_secondary_metrics(),
        }
        prior = _prior_cpu_mesh_value()
        if prior is not None and prior[0] > 0:
            doc["vs_baseline"] = round(doc["value"] / prior[0], 4)
            doc["baseline_source"] = (
                f"{prior[1]} cpu-mesh busbw {prior[0]} GB/s (NOT the TPU bar)"
            )
        if fallback:
            doc["fallback"] = True
            # WHY this round degraded, in the artifact itself (r02–r05
            # fell back with the cause only in lost stderr scrollback)
            doc["fallback_reason"] = fallback_reason or "unknown"
        lkg = _last_known_good_tpu() or _last_driver_captured_tpu()
        if lkg is not None:
            doc["last_known_good_tpu"] = lkg
    doc["platform"] = platform
    doc["n_devices"] = n
    doc["device_kind"] = devices[0].device_kind
    _stamp_attribution(doc)
    _stamp_autotune(doc)
    _stamp_hier_autotune(doc)
    _stamp_roofline(doc, primary_result)
    _stamp_matrix(doc)
    _stamp_serving(doc)
    _stamp_serving_disagg(doc)
    return doc


def _stamp_autotune(doc: dict) -> None:
    """Stamp the collective-autotune decision table (winning schedule
    per payload bucket + crossover points, probes/collectives.sweep)
    next to goodput_attribution — the tuned-collectives evidence the
    ROADMAP-item-2 goodput reclaim rides on. On the CPU fallback the
    table is interpret-mode numerics and says so (``interpret_mode``);
    it must never be read against a TPU bar. Guarded: a failing sweep
    costs this block, not the artifact."""
    try:
        import jax

        if len(jax.devices()) < 2:
            return  # nothing to tune on one chip
        from activemonitor_tpu.probes import collectives as collectives_probe

        on_tpu = doc.get("platform") == "tpu"
        # quick grid + allreduce family only on CPU (interpret-mode
        # timings are about table SHAPE, not magnitude, and the graft
        # contract test runs this line inside the tier-1 budget); a
        # mid-size grid over both families on TPU so the large-payload
        # rsag-vs-psum cell — where a zoo win is expected — lands in
        # the artifact without a 256 MB-per-schedule bill
        result = collectives_probe.sweep(
            sizes_mb=(1.0, 16.0, 64.0) if on_tpu else None,
            iters=3 if on_tpu else 2,
            quick=not on_tpu,
            collectives=("allreduce", "allgather") if on_tpu else ("allreduce",),
        )
        if result.details.get("skipped"):
            return
        doc["collective_autotune"] = {
            "interpret_mode": not on_tpu,
            "table": result.details["autotune_table"],
            "crossovers": result.details["crossovers"],
            "zoo_best_win": result.details["zoo_best_win"],
            "zoo_best_cell": result.details["zoo_best_cell"],
        }
    except Exception as exc:  # pragma: no cover - defensive
        print(f"autotune stamp failed: {exc!r}", file=sys.stderr)
    _stamp_grad_sync(doc)


def _stamp_grad_sync(doc: dict) -> None:
    """Stamp the training-step gradient-sync decision next to the
    collective_autotune table: race every all-reduce schedule at the
    probe model's dominant gradient payload on a dp-only mesh, then
    record the schedule ``training_step.grad_sync_plan`` resolves and
    its measured busbw ratio over the XLA builtin
    (``tuned_vs_builtin``). Both paths stamp it; CPU-fallback rounds
    are ``interpret_mode: true`` — table shape, never a TPU bar.
    Guarded: a failing tune costs this block, not the artifact."""
    try:
        import jax
        import jax.numpy as jnp

        if len(jax.devices()) < 2 or "collective_autotune" not in doc:
            return
        from activemonitor_tpu.models.probe_model import (
            ProbeModelConfig,
            tiny_config,
        )
        from activemonitor_tpu.parallel import autotune
        from activemonitor_tpu.parallel.mesh import make_mesh
        from activemonitor_tpu.probes.training_step import grad_sync_plan

        on_tpu = doc.get("platform") == "tpu"
        n = len(jax.devices())
        mesh = make_mesh(("data", "model"), (n, 1))
        cfg = ProbeModelConfig() if on_tpu else tiny_config()
        payload = grad_sync_plan(cfg, mesh)["largest_leaf_bytes"]
        tuned = autotune.tune(
            mesh, axis="data", collectives=("allreduce",),
            sizes_mb=(max(0.25, payload / 1e6),), dtype=jnp.float32, iters=2,
        )
        cell = next(iter(tuned.results["allreduce"].values()))
        plan = grad_sync_plan(cfg, mesh)
        entry = {
            "allreduce_schedule": plan["schedule"],
            "axis_n": plan["axis_n"],
            "payload_bytes": plan["largest_leaf_bytes"],
            "interpret_mode": not on_tpu,
        }
        if cell.get("xla", 0.0) > 0 and plan["schedule"] in cell:
            entry["tuned_vs_builtin"] = round(
                cell[plan["schedule"]] / cell["xla"], 3
            )
        doc["collective_autotune"]["training_step_grad_sync"] = entry
    except Exception as exc:  # pragma: no cover - defensive
        print(f"grad-sync stamp failed: {exc!r}", file=sys.stderr)


def _stamp_hier_autotune(doc: dict) -> None:
    """Stamp the hierarchical DCN×ICI autotune evidence next to
    ``collective_autotune``: the per-tier decision table (dcn cells
    suffixed ``@dcn``), the tuned latency-path threshold (payloads
    below it ride the full-payload few-round composition), and the
    best tiered-vs-flat busbw ratio over the swept grid. The device
    set is re-meshed into a synthetic (2, n/2) two-tier topology —
    single-process stand-in; probes/dcn.py owns the real cross-host
    tier — and CPU-fallback rounds are ``interpret_mode: true``, never
    read against a TPU bar. Guarded: a failing tune costs this block,
    not the artifact. ``ACTIVEMONITOR_BENCH_HIER=off`` disables."""
    if os.environ.get("ACTIVEMONITOR_BENCH_HIER", "") == "off":
        return
    try:
        import jax
        import jax.numpy as jnp

        from activemonitor_tpu.parallel.mesh import (
            make_synthetic_two_tier_mesh,
        )

        devices = jax.devices()
        n = len(devices)
        mesh = make_synthetic_two_tier_mesh(devices)
        if mesh is None:
            return  # no two-tier re-mesh to race
        from activemonitor_tpu.parallel import autotune

        on_tpu = doc.get("platform") == "tpu"
        # small grid: the latency-vs-bandwidth crossover lives at the
        # small end; one mid payload anchors the bandwidth side
        sizes = (0.016, 1.0, 16.0) if on_tpu else (0.004, 0.25)
        tuned = autotune.tune_hierarchical(
            mesh, sizes_mb=sizes, dtype=jnp.bfloat16,
            iters=3 if on_tpu else 2,
        )
        tiered_vs_flat = None
        best_cell = None
        for size_mb, row in tuned.variant_results.items():
            flat = row.get("flat", 0.0)
            if flat <= 0:
                continue
            for variant in ("bandwidth", "latency"):
                ratio = row.get(variant, 0.0) / flat
                if tiered_vs_flat is None or ratio > tiered_vs_flat:
                    tiered_vs_flat = round(ratio, 3)
                    best_cell = {"variant": variant, "size_mb": size_mb}
        doc["hierarchical_autotune"] = {
            "interpret_mode": not on_tpu,
            "mesh": {"dcn": 2, "ici": n // 2},
            "tier_table": autotune.table_as_dict(keys=tuned.keys),
            "latency_threshold_bytes": tuned.threshold_bytes,
            "threshold_source": tuned.threshold_source,
            "variant_busbw_gbps": {
                f"{size_mb}MB": {k: round(v, 3) for k, v in row.items()}
                for size_mb, row in tuned.variant_results.items()
            },
            "tiered_vs_flat": tiered_vs_flat,
            "tiered_vs_flat_cell": best_cell,
        }
    except Exception as exc:  # pragma: no cover - defensive
        print(f"hierarchical autotune stamp failed: {exc!r}", file=sys.stderr)


def _stamp_roofline(doc: dict, result) -> None:
    """Stamp the primary probe's roofline evidence (obs/roofline.py)
    into the artifact as ``roofline_summary`` — per metric prefix the
    bound, arithmetic intensity, fraction-of-roofline and cost source,
    plus any structured skip reasons — so every BENCH_r*.json says
    whether its fraction was measured against a real ceiling and where
    the cost numbers came from. CPU-fallback rounds carry
    ``interpret_mode: true`` with ``cost_source: model`` entries (or
    skips): labeled evidence, never read against a TPU bar. Guarded:
    a broken block costs this stamp, not the artifact."""
    try:
        block = dict(getattr(result, "roofline", None) or {})
        detail = (getattr(result, "details", None) or {}).get("roofline") or {}
        skipped = {
            prefix: entry["skipped"]
            for prefix, entry in detail.items()
            if isinstance(entry, dict) and "skipped" in entry
        }
        summary = {
            "interpret_mode": doc.get("platform") != "tpu",
            "metrics": {
                prefix: {
                    "bound": entry.get("bound"),
                    "intensity": round(float(entry.get("intensity", 0.0)), 4),
                    "fraction": round(float(entry.get("fraction", 0.0)), 4),
                    "cost_source": entry.get("cost_source"),
                }
                for prefix, entry in block.items()
            },
        }
        if skipped:
            summary["skipped"] = skipped
        doc["roofline_summary"] = summary
    except Exception as exc:  # pragma: no cover - defensive
        print(f"roofline stamp failed: {exc!r}", file=sys.stderr)


def _stamp_matrix(doc: dict) -> None:
    """Stamp the declarative scenario matrix's round summary
    (analysis/matrix.py) into the artifact as ``matrix_summary`` —
    per-cell values, hysteresis verdicts, roofline stamps, structured
    skips, and any confirmed regressions with their auto-bisect
    outcomes. BOTH paths stamp it: CPU-fallback rounds are
    ``interpret_mode: true`` with the round's ``fallback_reason``
    carried into every cell (the r02–r05 lesson — a wedged round must
    never again produce an artifact that silently omits the evidence
    block). Baselines persist across rounds in the BENCH_BASELINES.json
    sidecar next to this file (override: ACTIVEMONITOR_BENCH_BASELINES).
    Guarded: a failing matrix costs this block, not the artifact.
    ACTIVEMONITOR_BENCH_MATRIX=off disables, =full runs every cell on
    the CPU path too (default there is the quick 2-cell slice so the
    graft contract test stays inside the tier-1 budget)."""
    mode = os.environ.get("ACTIVEMONITOR_BENCH_MATRIX", "")
    if mode == "off":
        return  # before any import: =off must skip ALL matrix cost
    try:
        import jax

        from activemonitor_tpu.analysis import matrix as matrix_mod
        from activemonitor_tpu.obs.flightrec import FlightRecorder

        here = os.path.dirname(os.path.abspath(__file__))
        spec, spec_warning = matrix_mod.load_spec(
            os.path.join(here, "config", "bench_matrix.json")
        )
        on_tpu = doc.get("platform") == "tpu"
        cells, skipped = matrix_mod.expand(
            spec, n_devices=len(jax.devices())
        )
        if mode != "full" and not on_tpu:
            quick = matrix_mod.quick_slice(cells)
            # cells outside the slice are structured skips, not silent
            # holes: the artifact says WHY each cell has no measurement
            skipped.extend(
                matrix_mod.skipped_result(
                    cell,
                    matrix_mod.SKIP_QUICK,
                    "not in the interpret-mode quick slice "
                    "(ACTIVEMONITOR_BENCH_MATRIX=full runs every cell)",
                )
                for cell in cells
                if cell not in quick
            )
            cells = quick
        rated = None
        if on_tpu:
            from activemonitor_tpu.probes.rated import rated_for

            rated = rated_for(doc.get("device_kind", ""))
        executor = matrix_mod.make_executor(iters=3 if on_tpu else 2)
        sidecar = os.environ.get(
            "ACTIVEMONITOR_BENCH_BASELINES",
            os.path.join(here, matrix_mod.SIDECAR_BASENAME),
        )
        # confirmed regressions ship durable postmortems: one JSONL
        # bundle per transition, next to the sidecar (flightrec.jsonl)
        observatory = matrix_mod.MatrixObservatory(
            path=sidecar,
            rated_spec=rated,
            flightrec=FlightRecorder(
                flight_dir=os.path.dirname(os.path.abspath(sidecar))
            ),
        )
        results = [executor(cell) for cell in cells] + skipped
        summary = observatory.observe_round(
            results,
            executor=executor,
            interpret_mode=not on_tpu,
            fallback_reason=(
                doc.get("fallback_reason", "") if doc.get("fallback") else ""
            ),
        )
        if spec_warning is not None:
            summary["spec_warning"] = spec_warning
        doc["matrix_summary"] = summary
    except Exception as exc:  # pragma: no cover - defensive
        print(f"matrix stamp failed: {exc!r}", file=sys.stderr)


def _stamp_serving(doc: dict) -> None:
    """Stamp the continuous-batching serving probe's round evidence
    (probes/serving.py) into the artifact as ``serving_summary`` —
    tokens/s, TTFT/inter-token tails, batch occupancy, KV
    fragmentation, the continuous-vs-static consistency gate and the
    exact token-conservation ledger, plus the roofline verdict (or its
    structured skip). BOTH paths stamp it: CPU-fallback rounds are
    ``interpret_mode: true`` (tiny model, ``cost_source: model`` —
    never read against a TPU bar) and carry the round's
    ``fallback_reason`` like every other evidence block. Guarded: a
    failing soak costs this block, not the artifact.
    ``ACTIVEMONITOR_BENCH_SERVING=off`` disables."""
    if os.environ.get("ACTIVEMONITOR_BENCH_SERVING", "") == "off":
        return
    try:
        from activemonitor_tpu.probes import serving as serving_probe

        on_tpu = doc.get("platform") == "tpu"
        result = serving_probe.run(
            tiny=not on_tpu,
            n_requests=16 if on_tpu else 8,
            max_batch=8 if on_tpu else 4,
        )
        by_name = {m.name: m.value for m in result.metrics}
        summary = {
            "interpret_mode": not on_tpu,
            "ok": result.ok,
            "tokens_per_s": round(by_name["serving-tokens-per-s"], 2),
            "ttft_p50_ms": round(by_name["serving-ttft-p50-ms"], 3),
            "ttft_p99_ms": round(by_name["serving-ttft-p99-ms"], 3),
            "intertoken_p99_ms": round(
                by_name["serving-intertoken-p99-ms"], 3
            ),
            "batch_occupancy": round(by_name["serving-batch-occupancy"], 4),
            "kv_frag_ratio": round(by_name["serving-kv-frag-ratio"], 4),
            "kv_bytes_per_token": by_name["serving-kv-bytes-per-token"],
            "consistency": by_name["serving-consistency"] == 1.0,
            "conservation": result.details["conservation"],
            "refusals": result.details["refusals"],
            # the verdict when a rated roofline exists (TPU), else the
            # structured skip reason — never a silent omission
            "roofline": (result.details.get("roofline") or {}).get("serving"),
        }
        if doc.get("fallback"):
            summary["fallback_reason"] = doc.get("fallback_reason", "")
        doc["serving_summary"] = summary
    except Exception as exc:  # pragma: no cover - defensive
        print(f"serving stamp failed: {exc!r}", file=sys.stderr)


def _stamp_serving_disagg(doc: dict) -> None:
    """Stamp the disaggregated-serving probe's round evidence
    (probes/serving.run_disagg) into the artifact as ``serving_disagg``
    — the colocated-vs-split TTFT comparison under one scripted cost
    model, the pool-boundary migration ledger, the per-tenant prefix
    ledger, and the speculative acceptance fraction. BOTH paths stamp
    it: CPU-fallback rounds are ``interpret_mode: true`` (tiny model,
    ``cost_source: scripted`` — a policy/ledger artifact, never read
    against a TPU bar) and carry the round's ``fallback_reason`` like
    every other evidence block. Guarded: a failing soak costs this
    block, not the artifact. ``ACTIVEMONITOR_BENCH_SERVING_DISAGG=off``
    disables."""
    if os.environ.get("ACTIVEMONITOR_BENCH_SERVING_DISAGG", "") == "off":
        return
    try:
        from activemonitor_tpu.probes import serving as serving_probe

        on_tpu = doc.get("platform") == "tpu"
        result = serving_probe.run_disagg(
            tiny=not on_tpu,
            n_requests=16 if on_tpu else 10,
        )
        block = dict(result.details["serving_disagg"])
        block["ttft_improvement"] = round(block["ttft_improvement"], 4)
        block["interpret_mode"] = not on_tpu
        block["ok"] = result.ok
        block["conservation"] = result.details["conservation"]
        block["prefix_ledger"] = result.details["prefix_ledger"]
        if doc.get("fallback"):
            block["fallback_reason"] = doc.get("fallback_reason", "")
        doc["serving_disagg"] = block
    except Exception as exc:  # pragma: no cover - defensive
        print(f"serving disagg stamp failed: {exc!r}", file=sys.stderr)


def _stamp_attribution(doc: dict) -> None:
    """Stamp the round's lost-goodput attribution next to
    fallback_reason, using the controller's taxonomy
    (obs/attribution.py) — so BENCH_r*.json records WHY a round lost
    goodput (CPU fallback vs probe hang vs real regression), not just
    that it did. Guarded: a broken import must not cost the artifact."""
    try:
        from activemonitor_tpu.obs.attribution import classify_bench_round

        doc["goodput_attribution"] = classify_bench_round(doc)
    except Exception as exc:  # pragma: no cover - defensive
        print(f"attribution stamp failed: {exc!r}", file=sys.stderr)


def main() -> int:
    if "--measure" in sys.argv:
        # child mode: do the real measurement and print the JSON line.
        # Only the TPU path spawns a child (CPU runs measure in-process
        # — nothing to hang on), so this is never a CPU measurement.
        print(json.dumps(_measure(want_cpu=False)))
        return 0

    # known-CPU runs have no tunnel to hang on — measure in-process
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        print(json.dumps(_measure(want_cpu=True)))
        return 0

    reachable, fallback_reason = _device_reachable()
    if reachable:
        # the measurement itself can also hit a mid-run wedge — run it
        # killable so the driver never hangs on us
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--measure"],
                timeout=_MEASURE_TIMEOUT,
                capture_output=True,
            )
        except subprocess.TimeoutExpired:
            fallback_reason = (
                f"TPU measurement hung past {_MEASURE_TIMEOUT:.0f}s "
                "(tunnel wedged mid-run?)"
            )
            print(fallback_reason, file=sys.stderr)
        else:
            sys.stderr.write(proc.stderr.decode(errors="replace"))
            lines = [
                ln for ln in proc.stdout.decode(errors="replace").splitlines() if ln
            ]
            if proc.returncode == 0 and lines:
                try:
                    doc = json.loads(lines[-1])
                except json.JSONDecodeError:
                    doc = None
                if doc is not None:
                    print(json.dumps(doc))
                    return 0
            fallback_reason = (
                f"TPU measurement exited with {proc.returncode}; "
                "stdout tail: " + " | ".join(lines[-3:])
            )
            print(fallback_reason, file=sys.stderr)

    print("falling back to the virtual CPU mesh", file=sys.stderr)
    _force_cpu_mesh()
    print(
        json.dumps(
            _measure(want_cpu=True, fallback=True, fallback_reason=fallback_reason)
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
