"""Probe protocol and the custom-metrics output contract.

A probe is a callable returning a :class:`ProbeResult`. Run as a
workflow payload (any engine), its last stdout line is the JSON
custom-metrics contract the controller parses into Prometheus series
(reference contract: internal/metrics/collector.go:68-115 —
``{"metrics": [{name, value, metrictype, help}]}``), and its exit code
is the probe verdict Argo/the local engine turn into Succeeded/Failed.

Beyond the reference, the contract carries an optional ``timings``
block — ``{"timings": {phase: seconds}}`` — measured INSIDE the payload
with :class:`PhaseTimings` (Reframe-style, PAPERS.md arXiv:2404.10536:
regression detection needs per-phase timings from inside the benchmark,
not just end-to-end latency). The controller turns it into
``healthcheck_phase_seconds{healthcheck_name,phase}`` histograms, AND
feeds it to goodput attribution (obs/attribution.py): a lost run whose
timed seconds are dominated by compile-vocabulary phases (``compile``,
``init``, ``jit``…) is attributed to the ``compile`` bucket — so name
your phases from the probe's real structure (``init``/``compile``/
``execute``), not generically. Entries the controller cannot parse are
counted in ``healthcheck_phase_timings_skipped_total{reason}`` — watch
it after upgrading probes and controller at different times (contract
drift is visible on /metrics, not just in logs).

The contract also carries an optional ``roofline`` block —
``{"roofline": {prefix: {bound, intensity, fraction, cost_source,
...}}}`` (obs/roofline.py ``VERDICT_FIELDS``) — the cost-model verdict
under each ``<prefix>-roofline-fraction`` gauge: which roofline the
kernel is on (compute/memory/comm), where it sits against that ceiling,
and whether the numbers came from XLA's compile-time cost analysis
(``cost_source: xla``) or the probe's analytic fallback (``model``,
interpret mode / old JAX — never compared against a TPU bar). The
controller exports it as ``healthcheck_probe_roofline_fraction{bound}``
/ ``healthcheck_probe_arithmetic_intensity`` /
``healthcheck_hbm_peak_bytes`` and threads it through /statusz,
``am-tpu roofline``, goodput attribution, and flight bundles.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List


class PhaseTimings(Dict[str, float]):
    """Phase-name → seconds accumulator with a ``phase()`` context
    manager. A plain dict underneath, so it drops straight into
    :attr:`ProbeResult.timings`; re-entering a phase name accumulates
    (a probe may iterate a phase). The time source is injectable for
    deterministic tests."""

    def __init__(self, monotonic: Callable[[], float] = time.monotonic):
        super().__init__()
        self._monotonic = monotonic

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """``with timings.phase("compile"): ...`` — time the block,
        accumulating into ``self[name]``. The phase is recorded even
        when the block raises: a probe that dies mid-phase still
        reports where the time went."""
        start = self._monotonic()
        try:
            yield
        finally:
            elapsed = max(0.0, self._monotonic() - start)
            self[name] = self.get(name, 0.0) + elapsed


@dataclass
class ProbeMetric:
    name: str
    value: float
    help: str = ""
    metrictype: str = "gauge"

    def to_contract(self) -> dict:
        return {
            "name": self.name,
            "value": float(self.value),
            "metrictype": self.metrictype,
            "help": self.help,
        }


@dataclass
class ProbeResult:
    ok: bool
    summary: str
    metrics: List[ProbeMetric] = field(default_factory=list)
    details: Dict = field(default_factory=dict)
    # phase-name -> seconds, measured inside the payload (PhaseTimings);
    # empty means the probe doesn't attribute its time and the contract
    # line stays byte-identical to the pre-timings form
    timings: Dict[str, float] = field(default_factory=dict)
    # metric-prefix -> roofline verdict (obs/roofline.py): the cost-model
    # evidence under each roofline-fraction gauge; skips stay in
    # `details` only, so the contract carries verdicts exclusively
    roofline: Dict[str, Dict] = field(default_factory=dict)

    def contract_line(self) -> str:
        doc: Dict = {"metrics": [m.to_contract() for m in self.metrics]}
        if self.timings:
            doc["timings"] = {
                name: float(seconds) for name, seconds in self.timings.items()
            }
        if self.roofline:
            doc["roofline"] = {
                prefix: dict(entry) for prefix, entry in self.roofline.items()
            }
        return json.dumps(doc)

    def emit(self) -> int:
        """Human-readable report to stderr, contract line to stdout,
        exit code for the engine."""
        print(("OK: " if self.ok else "FAIL: ") + self.summary, file=sys.stderr)
        for key, value in sorted(self.details.items()):
            print(f"  {key}: {value}", file=sys.stderr)
        for name, seconds in sorted(self.timings.items()):
            print(f"  phase {name}: {seconds:.3f}s", file=sys.stderr)
        print(self.contract_line(), flush=True)
        return 0 if self.ok else 1
