"""Structured logging for the controller process.

Reference parity: controller-runtime binds zap's flagset
(reference: cmd/main.go:146-152), giving operators ``--zap-encoder
json|console`` and a level flag. Here the same two knobs are
``--log-format json|text`` and ``--log-level``, wired in __main__.
JSON lines carry the fields log pipelines key on (ts/level/logger/msg,
plus the exception traceback when present), every ``extra={...}``
structured field the call site attached, and — inside an active trace
span — ``trace_id``/``span`` so a log line joins its reconcile cycle's
trace, events, and metrics.
"""

from __future__ import annotations

import json
import logging

# the attribute names every LogRecord carries by construction — anything
# beyond these on a record's __dict__ arrived via ``extra={...}`` (or an
# adapter) and is a structured field the caller wants emitted. Derived
# from a probe record, not hardcoded, so interpreter additions (3.12's
# ``taskName``) never leak into log lines as phantom extras.
_STANDARD_ATTRS = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        # extra={...} fields survive (the silent-drop fix): anything the
        # call site attached rides the line, losing only on a collision
        # with the four envelope keys above
        for key, value in record.__dict__.items():
            if key in _STANDARD_ATTRS or key.startswith("_") or key in doc:
                continue
            doc[key] = value
        # trace correlation: a line logged inside a span carries its
        # trace so `grep trace_id` reconstructs one cycle across logs,
        # events, and /debug/traces. Imported lazily: logfmt must stay
        # importable from anywhere without dragging the obs package in.
        from activemonitor_tpu.obs.trace import current_span

        span = current_span()
        if span is not None:
            doc.setdefault("trace_id", span.trace_id)
            doc.setdefault("span", span.name)
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc, default=str)


def configure_logging(level: str = "INFO", fmt: str = "text") -> None:
    """Process-wide logging setup; ``fmt`` is "text" (console) or
    "json" (structured lines)."""
    if fmt == "json":
        handler = logging.StreamHandler()
        handler.setFormatter(JsonFormatter())
        logging.basicConfig(level=level.upper(), handlers=[handler], force=True)
    else:
        logging.basicConfig(
            level=level.upper(),
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
            force=True,
        )
