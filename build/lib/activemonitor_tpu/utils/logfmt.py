"""Structured logging for the controller process.

Reference parity: controller-runtime binds zap's flagset
(reference: cmd/main.go:146-152), giving operators ``--zap-encoder
json|console`` and a level flag. Here the same two knobs are
``--log-format json|text`` and ``--log-level``, wired in __main__.
JSON lines carry the fields log pipelines key on (ts/level/logger/msg,
plus the exception traceback when present).
"""

from __future__ import annotations

import json
import logging


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S%z"),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def configure_logging(level: str = "INFO", fmt: str = "text") -> None:
    """Process-wide logging setup; ``fmt`` is "text" (console) or
    "json" (structured lines)."""
    if fmt == "json":
        handler = logging.StreamHandler()
        handler.setFormatter(JsonFormatter())
        logging.basicConfig(level=level.upper(), handlers=[handler], force=True)
    else:
        logging.basicConfig(
            level=level.upper(),
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
            force=True,
        )
