"""ResilienceCoordinator — the one object that owns degradation policy.

The reconciler owns a coordinator the same way it owns the tracer and
the fleet SLO aggregate. It bundles the three containment mechanisms of
docs/resilience.md behind one façade so the reconciler, the manager and
``/statusz`` can never disagree about whether the controller is
degraded:

- the shared :class:`~activemonitor_tpu.resilience.breaker.CircuitBreaker`
  around the kube transport's mutating calls and the engines'
  submit/poll paths;
- the per-check :class:`~activemonitor_tpu.resilience.health.
  CheckStateTracker` (healthy → flapping → quarantined);
- the fleet-wide remedy :class:`~activemonitor_tpu.resilience.storm.
  TokenBucket` (``--remedy-rate``).

Degraded mode = the breaker is not closed. While degraded:

- reconcile requeues stretch: each delay is drawn with FULL JITTER from
  ``[0, time remaining in the breaker's open window]`` (floored at the
  1 s base) — longest right after the trip, tightening to the base as
  recovery nears, and spread so the fleet doesn't re-converge on the
  apiserver in one synchronized wave. The envelope is computed from the
  clock, deliberately NOT from a shared mutable backoff schedule: a
  shared pacer advanced per call collapses to its floor after a handful
  of draws once many checks are degraded at once;
- status writes queue here for replay (latest status per check wins; the
  queue is also the freshest-truth overlay the reconciler consults so a
  stale durable status can't double-submit a run);
- ``healthcheck_controller_degraded`` reads 1 and ``/statusz`` says so.
"""

from __future__ import annotations

import collections
import logging
import random
from typing import Optional, Tuple

from activemonitor_tpu.resilience.breaker import (
    STATE_CLOSED,
    STATE_OPEN,
    CircuitBreaker,
)
from activemonitor_tpu.resilience.health import CheckStateTracker
from activemonitor_tpu.resilience.storm import TokenBucket
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.resilience")

# degraded-cadence floor: even right before recovery the controller
# never requeues tighter than the reference's 1 s error cadence
DEGRADED_MIN_DELAY = 1.0


class ResilienceCoordinator:
    def __init__(
        self,
        clock: Optional[Clock] = None,
        metrics=None,
        *,
        breaker: Optional[CircuitBreaker] = None,
        checks: Optional[CheckStateTracker] = None,
        remedy_rate: float = 0.0,
        rng: Optional[random.Random] = None,
    ):
        self.clock = clock or Clock()
        self.metrics = metrics
        self.breaker = breaker or CircuitBreaker("api", clock=self.clock)
        # the coordinator funnels every transition (including those of an
        # injected breaker) into the degraded gauge/pacer bookkeeping
        self.breaker._on_transition = self._on_breaker_transition
        self.checks = checks or CheckStateTracker()
        self._rng = rng
        # wired by the reconciler (obs/flightrec.py): a breaker trip is
        # one of the flight recorder's trigger transitions — the bundle
        # snapshots what the fleet looked like the moment the controller
        # went degraded. None (standalone) records nothing.
        self.flightrec = None
        self.remedy_bucket: Optional[TokenBucket] = None
        self.configure_remedy_rate(remedy_rate)
        # key -> queued HealthCheck (latest status wins); insertion order
        # is replay order
        self._status_queue: "collections.OrderedDict[str, object]" = (
            collections.OrderedDict()
        )
        if self.metrics is not None:
            self.metrics.set_degraded(False)
            self.metrics.set_status_write_queue_depth(0)

    # -- degraded mode --------------------------------------------------
    @property
    def degraded(self) -> bool:
        """True while the breaker is open or probing (half-open): the
        controller keeps reconciling but fails soft — stretched cadence,
        queued status writes."""
        return self.breaker.state != STATE_CLOSED

    def _on_breaker_transition(self, old: str, new: str) -> None:
        degraded = new != STATE_CLOSED
        log.log(
            logging.WARNING if degraded else logging.INFO,
            "controller %s (breaker %r: %s -> %s)",
            "DEGRADED" if degraded else "recovered",
            self.breaker.name,
            old,
            new,
        )
        if self.metrics is not None:
            self.metrics.set_degraded(degraded)
        if new == STATE_OPEN and self.flightrec is not None:
            # the trip itself is the postmortem moment: snapshot the
            # breaker stats and recent spans before the outage noise
            # wraps the rings (flightrec never raises back into here;
            # imported lazily — obs/flightrec sits above this layer)
            from activemonitor_tpu.obs.flightrec import KIND_BREAKER

            self.flightrec.record(
                KIND_BREAKER, breaker=self.breaker.snapshot()
            )

    def refresh(self) -> None:
        """Poll time-driven state (open → half-open happens on state
        reads, which fire the transition callback) so the gauge moves
        even when no traffic is flowing. Called from the manager's
        resilience loop."""
        degraded = self.degraded  # the read drives open -> half-open
        if self.metrics is not None:
            self.metrics.set_degraded(degraded)

    def requeue_delay(self, base: float) -> float:
        """The requeue/retry delay to use right now: ``base`` when
        healthy; while degraded, a full-jitter draw from
        ``[0, time remaining in the open window]``, floored at ``base``.
        Time-based on purpose — the envelope is the breaker's own
        ``retry_after()``, so concurrent callers each get an independent
        draw and arrivals spread across the remainder of the outage (a
        shared advancing backoff schedule would collapse to its floor
        after a handful of fleet-wide calls). In half-open the envelope
        is gone and retries tighten to ``base`` — fast recovery probing."""
        if not self.degraded:
            return base
        envelope = max(DEGRADED_MIN_DELAY, self.breaker.retry_after())
        uniform = self._rng.uniform if self._rng is not None else random.uniform
        return max(base, uniform(0.0, envelope))

    # -- status-write replay queue --------------------------------------
    def queue_status_write(self, hc) -> None:
        """Park a status write for replay once the breaker closes. The
        latest status per check wins; replay order is FIFO by first
        queueing."""
        key = hc.key
        queued = self._status_queue.get(key)
        if queued is not None:
            queued.status = hc.status.model_copy(deep=True)
        else:
            self._status_queue[key] = hc.deepcopy()
        log.warning(
            "status write for %s queued for replay (%d queued; breaker %s)",
            key,
            len(self._status_queue),
            self.breaker.state,
        )
        self._sync_queue_gauge()

    def queued_status(self, key: str):
        """The freshest not-yet-persisted status for a check, or None.
        The reconciler overlays this on the (stale) durable status so a
        queued-but-unwritten run cannot be double-submitted."""
        hc = self._status_queue.get(key)
        return hc.status if hc is not None else None

    def next_status_write(self) -> Optional[Tuple[str, object]]:
        """Pop the oldest queued write for replay (None when empty).
        Callers re-queue via :meth:`requeue_status_write` on failure."""
        if not self._status_queue:
            return None
        key, hc = self._status_queue.popitem(last=False)
        self._sync_queue_gauge()
        return key, hc

    def requeue_status_write(self, key: str, hc) -> None:
        """A replay attempt failed: put the write back at the front
        unless a fresher status was queued meanwhile."""
        if key not in self._status_queue:
            self._status_queue[key] = hc
            self._status_queue.move_to_end(key, last=False)
        self._sync_queue_gauge()

    def drop_status_write(self, key: str) -> None:
        """The check is gone: its queued write is moot."""
        self._status_queue.pop(key, None)
        self._sync_queue_gauge()

    def drop_status_writes_matching(self, predicate) -> int:
        """Shard handoff: queued writes for keys matching ``predicate``
        would only be fenced at replay (the shard's new owner is
        authoritative) — drop them now. Returns how many were dropped."""
        dropped = [key for key in self._status_queue if predicate(key)]
        for key in dropped:
            self._status_queue.pop(key, None)
        if dropped:
            self._sync_queue_gauge()
        return len(dropped)

    def pending_status_writes(self) -> int:
        return len(self._status_queue)

    def queued_status_keys(self) -> list:
        """Keys with a write parked for replay — the shard layer checks
        these before a voluntary handoff (a shed must not strand a
        recorded run in this process's queue)."""
        return list(self._status_queue)

    def _sync_queue_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.set_status_write_queue_depth(len(self._status_queue))

    # -- remedy storm control -------------------------------------------
    def configure_remedy_rate(self, rate_per_minute: float) -> None:
        """Install, adjust, or remove (rate <= 0) the fleet-wide remedy
        cap. Called at manager construction from --remedy-rate, and on
        every shard handoff in a sharded fleet (the replica's share of
        the fleet cap follows its owned-shard count). Adjusting a live
        bucket preserves its accrued tokens — a handoff never mints a
        fresh burst of remedy budget."""
        if rate_per_minute and rate_per_minute > 0:
            if self.remedy_bucket is not None:
                self.remedy_bucket.set_rate(rate_per_minute)
            else:
                self.remedy_bucket = TokenBucket(rate_per_minute, clock=self.clock)
        else:
            self.remedy_bucket = None

    def admit_remedy(self) -> bool:
        """Take a fleet-wide remedy token. Always True when no cap is
        configured."""
        if self.remedy_bucket is None:
            return True
        return self.remedy_bucket.try_take()

    def remedy_tokens(self) -> Optional[float]:
        """Tokens remaining (None when uncapped) — /statusz and the CLI."""
        if self.remedy_bucket is None:
            return None
        return self.remedy_bucket.available()

    # -- lifecycle ------------------------------------------------------
    def forget(self, key: str) -> None:
        """Deleted check: drop tracker state and any queued write."""
        self.checks.forget(key)
        self.drop_status_write(key)

    # -- introspection --------------------------------------------------
    def snapshot(self) -> dict:
        """The /statusz ``fleet`` resilience block."""
        return {
            "degraded": self.degraded,
            "breaker": self.breaker.snapshot(),
            "status_writes_queued": len(self._status_queue),
            "remedy_tokens": self.remedy_tokens(),
        }
