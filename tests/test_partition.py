"""parallel/partition.py — the one sharding surface.

Rule-matching semantics (precedence, fallback, validation), the
shard/gather fns, the single shard_map entry point, and the tuned
collective dispatch it unlocked in the ops-layer hot paths (the
training-step gradient sync asserted through the schedules' traced
``_hop`` choke point).
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from activemonitor_tpu.parallel import autotune, partition, schedules
from activemonitor_tpu.parallel.mesh import make_1d_mesh, make_2d_mesh, make_mesh


@pytest.fixture(autouse=True)
def _clean_autotune_table():
    autotune.clear()
    yield
    autotune.clear()


# ---------------------------------------------------------------------------
# named_tree_map / rule matching
# ---------------------------------------------------------------------------


def test_named_tree_map_paths_cover_dicts_and_lists():
    tree = {"a": {"b": 1}, "layers": [{"w": 2}, {"w": 3}]}
    seen = {}
    partition.named_tree_map(
        lambda name, leaf: seen.setdefault(name, leaf), tree
    )
    assert seen == {"a/b": 1, "layers/0/w": 2, "layers/1/w": 3}


def test_first_match_wins_over_later_more_specific_rule():
    """Precedence is first-match, not most-specific: an earlier broad
    rule shadows a later exact one (so rules are ordered
    most-specific-first by convention)."""
    tree = {"layers": {"wqkv": jnp.zeros((4, 4))}}
    rules = (
        ("w", P("model", None)),  # broad, first: wins
        (r"^layers/wqkv$", P(None, "model")),  # exact, second: shadowed
    )
    specs = partition.match_partition_rules(rules, tree)
    assert specs["layers"]["wqkv"] == P("model", None)
    # flipped order: the exact rule now wins
    specs = partition.match_partition_rules(tuple(reversed(rules)), tree)
    assert specs["layers"]["wqkv"] == P(None, "model")


def test_unmatched_leaf_falls_back_to_replicated():
    tree = {"w": jnp.zeros((4, 4)), "stray": jnp.zeros((8,))}
    specs = partition.match_partition_rules((("^w$", P("model", None)),), tree)
    assert specs["w"] == P("model", None)
    assert specs["stray"] == P()  # replicated, never an error by default
    with pytest.raises(ValueError, match="no partition rule matched.*stray"):
        partition.match_partition_rules(
            (("^w$", P("model", None)),), tree, on_unmatched="error"
        )


def test_scalars_and_size_one_leaves_never_partition():
    tree = {
        "count": jnp.zeros(()),
        "one": jnp.zeros((1, 1)),
        "w": jnp.zeros((4, 4)),
    }
    # a greedy rule matches everything; scalars still resolve P()
    specs = partition.match_partition_rules(((".*", P("model", None)),), tree)
    assert specs["count"] == P()
    assert specs["one"] == P()
    assert specs["w"] == P("model", None)


def test_rule_naming_absent_mesh_axis_is_a_validation_error():
    """A rules-dict typo fails up front with the axis name — never a
    tracer crash from inside shard_map."""
    mesh = make_2d_mesh()
    tree = {"w": jnp.zeros((4, 4))}
    with pytest.raises(ValueError, match="sp.*absent from the mesh"):
        partition.match_partition_rules(
            (("^w$", P("sp", None)),), tree, mesh=mesh
        )
    with pytest.raises(ValueError, match="absent from the mesh"):
        partition.validate_specs({"w": P(None, ("data", "nope"))}, mesh)
    # the shard_map entry point guards the same way
    with pytest.raises(ValueError, match="absent from the mesh"):
        partition.shard_map(
            lambda x: x, mesh=mesh, in_specs=P("bogus"), out_specs=P("bogus"),
            check_vma=False,
        )
    with pytest.raises(ValueError, match="absent from the mesh"):
        partition.shard_map(
            lambda x: x, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False, axis_names=frozenset({"phantom"}),
        )


def test_mapping_rules_and_precedence_order_preserved():
    tree = {"wq": jnp.zeros((4, 4))}
    specs = partition.match_partition_rules(
        {"wq": P("model", None), ".*": P()}, tree
    )
    assert specs["wq"] == P("model", None)


# ---------------------------------------------------------------------------
# shard/gather fns + the entry point
# ---------------------------------------------------------------------------


def test_shard_tree_places_leaves_on_resolved_shardings():
    mesh = make_2d_mesh()
    tree = {"w": jnp.arange(32.0).reshape(8, 4), "b": jnp.arange(8.0)}
    rules = (("^w$", P("data", "model")), ("^b$", P(None)),)
    sharded, specs = partition.shard_tree(tree, rules, mesh)
    assert specs["w"] == P("data", "model")
    assert sharded["w"].sharding.spec == P("data", "model")
    # gather fns invert the placement
    gather = partition.make_gather_fns(specs, mesh)
    back = jax.tree.map(lambda fn, x: fn(x), gather, sharded)
    assert (back["w"] == tree["w"]).all()
    assert (back["b"] == tree["b"]).all()


def test_shard_map_entry_point_runs_a_collective():
    mesh = make_1d_mesh("ici")
    n = mesh.devices.size
    fn = partition.shard_map(
        lambda x: jax.lax.psum(x, "ici"),
        mesh=mesh, in_specs=P("ici", None), out_specs=P(None, None),
        check_vma=False,
    )
    out = fn(jnp.ones((n * 2, 3)))
    assert (out == n).all()


def test_compat_adapter_has_exactly_one_call_site():
    """The one-sharding-surface invariant, asserted structurally: the
    only module importing the compat shard_map adapter is
    parallel/partition.py (the lint twin checks the rule fires; this
    checks the tree actually honors it)."""
    import ast
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    importers = []
    for path in sorted((repo / "activemonitor_tpu").rglob("*.py")) + sorted(
        (repo / "tests").glob("*.py")
    ):
        if path.name == "compat.py":
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and (node.module or "").endswith(
                "compat"
            ):
                if any(a.name == "shard_map" for a in node.names):
                    importers.append(str(path))
    assert importers == [
        str(repo / "activemonitor_tpu" / "parallel" / "partition.py")
    ]


# ---------------------------------------------------------------------------
# tuned dispatch in the ops-layer hot paths
# ---------------------------------------------------------------------------


def _record_for_every_octave(collective, n, payloads, schedule, dtype):
    for payload in payloads:
        autotune.record(
            collective, n, payload, dtype, {schedule: 10.0, "xla": 1.0}
        )


def test_training_step_grad_sync_dispatches_tuned_schedule():
    """The acceptance gate: with the decision table tuned,
    autotune.all_reduce(schedule="auto") demonstrably runs in the
    training-step gradient sync — asserted via the schedules' traced
    ``_hop`` choke point, and the chosen schedule lands in the probe's
    stdout-contract plan."""
    import math

    from activemonitor_tpu.models.probe_model import init_params, tiny_config
    from activemonitor_tpu.probes import training_step as ts

    cfg = tiny_config()
    mesh = make_mesh(("data", "model"), (4, 1), devices=jax.devices()[:4])
    abstract = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    payloads = {
        int(math.prod(leaf.shape)) * 4 for leaf in jax.tree.leaves(abstract)
    }
    _record_for_every_octave("allreduce", 4, payloads, "rsag", jnp.float32)

    plan = ts.grad_sync_plan(cfg, mesh)
    assert plan["schedule"] == "rsag"
    assert plan["by_schedule"] == {"rsag": len(jax.tree.leaves(abstract))}

    step_fn, params, opt_state, data_sh = ts.build_sharded_train_step(
        cfg, mesh, grad_sync="auto"
    )
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (8, 17), 0, cfg.vocab_size),
        data_sh,
    )
    schedules._HOP_LOG = log = []
    try:
        step_fn.lower(params, opt_state, tokens)
    finally:
        schedules._HOP_LOG = None
    tags = {tag for tag, _step in log}
    assert tags == {"rsag-rs", "rsag-ag"}, tags


def test_grad_sync_explicit_matches_implicit_when_untuned():
    """Untuned "auto" resolves to the XLA psum: the explicit sync's
    loss matches the implicit (XLA-inserted) reduction on a dp-only
    mesh to float tolerance (the sync computes the identical global
    mean as a mean-of-equal-shard-means — same math, reassociated),
    so flipping the default cost nothing."""
    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.probes import training_step as ts

    cfg = tiny_config()
    mesh = make_mesh(("data", "model"), (4, 1), devices=jax.devices()[:4])
    tokens = jax.random.randint(jax.random.key(1), (8, 17), 0, cfg.vocab_size)
    losses = {}
    for grad_sync in ("implicit", "auto"):
        step_fn, params, opt_state, data_sh = ts.build_sharded_train_step(
            cfg, mesh, grad_sync=grad_sync
        )
        placed = jax.device_put(tokens, data_sh)
        for _ in range(2):
            params, opt_state, loss = step_fn(params, opt_state, placed)
        losses[grad_sync] = float(loss)
    assert losses["implicit"] == pytest.approx(losses["auto"], rel=1e-4), losses


def test_grad_sync_gates_fall_back_to_implicit():
    from activemonitor_tpu.probes import training_step as ts

    # live non-data axis: compiler keeps the reduction
    mesh = make_2d_mesh()  # (2, 4) on the 8-device CPU platform
    mode, reason = ts.resolve_grad_sync(mesh, "dense", "auto")
    assert mode == "implicit" and "model" in reason
    # no data axis to reduce over
    mode, reason = ts.resolve_grad_sync(make_1d_mesh("ici"), "dense", "auto")
    assert mode == "implicit"
    # ring attention runs its own shard_map — cannot nest
    dp = make_mesh(("data", "sp"), (4, 2))
    assert ts.resolve_grad_sync(dp, "ring", "auto")[0] == "implicit"
    dp_only = make_mesh(("data", "model"), (8, 1))
    assert ts.resolve_grad_sync(dp_only, "dense", "auto") == ("explicit", "")
    # accumulation keeps the global-batch % accum_steps contract: the
    # sync body would split the LOCAL shard instead
    mode, reason = ts.resolve_grad_sync(dp_only, "dense", "auto", accum_steps=4)
    assert mode == "implicit" and "accum" in reason
    with pytest.raises(ValueError, match="grad_sync"):
        ts.resolve_grad_sync(dp_only, "dense", "bogus")


def test_pipeline_final_combine_dispatches_tuned_schedule():
    """The pipeline's output combine rides the tuned surface: tune the
    combine payload's octave and the traced hop log shows the zoo
    schedule instead of the builtin psum."""
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        init_params,
    )
    from activemonitor_tpu.ops.pipeline import (
        pipeline_forward_blocks,
        stack_layer_params,
    )

    cfg = ProbeModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16, dtype=jnp.float32,
    )
    mesh = make_mesh(("pp",), (2,), devices=jax.devices()[:2])
    params = init_params(jax.random.key(0), cfg)
    stacked = stack_layer_params(params["layers"])
    x = jax.random.normal(jax.random.key(1), (2, 8, cfg.d_model), jnp.float32)
    # combine payload: [M=2, mb=1, S=8, D=16] f32
    payload = 2 * 1 * 8 * 16 * 4
    _record_for_every_octave("allreduce", 2, {payload}, "tree", jnp.float32)
    schedules._HOP_LOG = log = []
    try:
        out = pipeline_forward_blocks(stacked, x, cfg, mesh, "pp")
    finally:
        schedules._HOP_LOG = None
    tags = {tag for tag, _step in log}
    assert {"tree-reduce", "tree-bcast"} <= tags, tags
    # untuned: the builtin psum — bitwise-identical output
    autotune.clear()
    want = pipeline_forward_blocks(stacked, x, cfg, mesh, "pp")
    assert jnp.allclose(out, want, atol=1e-6)


def test_moe_dispatch_gather_rides_tuned_schedule():
    from activemonitor_tpu.ops.moe import (
        init_moe_params,
        moe_ffn_expert_parallel,
    )

    mesh = make_1d_mesh("ep")
    n = mesh.devices.size
    params = init_moe_params(jax.random.key(0), d_model=16, d_ff=32, n_experts=8)
    x = jax.random.normal(jax.random.key(1), (8 * n, 16), jnp.float32)
    shard_bytes = (x.shape[0] // n) * 16 * 4
    # all_gather decisions key on the GATHERED payload (x n)
    _record_for_every_octave(
        "allgather", n, {shard_bytes * n}, "ring", jnp.float32
    )
    fn = lambda p, x: moe_ffn_expert_parallel(p, x, mesh, "ep")
    schedules._HOP_LOG = log = []
    try:
        got = jax.jit(fn)(params, x)
    finally:
        schedules._HOP_LOG = None
    assert {tag for tag, _step in log} == {"ag-ring"}
    autotune.clear()
    want = jax.jit(fn)(params, x)
    assert jnp.max(jnp.abs(got - want)) < 1e-5
