"""Lightweight span tracer for the probe lifecycle.

The reference operator has no tracing at all: between ``enqueue()`` and
the final status write a HealthCheck cycle is invisible, which is
exactly the window where a slow manifest fetch, a hung engine submit,
or a starved workqueue hides. One trace per reconcile cycle with
per-phase durations (dequeue → parse → submit → poll → status-write →
remedy) makes that window attributable — the prerequisite for goodput
work (PAPERS.md: per-cycle time attribution).

Design constraints that shaped this module:

- **contextvar propagation, explicit handoff across the queue.** The
  current span lives in a :mod:`contextvars` variable, so it follows
  ``await`` chains and ``asyncio.create_task`` (which snapshots the
  context) for free — the reconciler's detached watch task inherits
  the cycle's trace without any plumbing. The one place context cannot
  flow by itself is the workqueue (enqueue happens on the watch task,
  dequeue on a worker task that existed first), so the manager carries
  the trace id in its pending-key table and the worker re-roots it.
- **injectable clock.** All timestamps come from
  :class:`~activemonitor_tpu.utils.clock.Clock`, so fake-clock tests
  assert exact durations, and span timing composes with the repo's
  no-sleeps test discipline.
- **bounded memory.** Finished spans land in a ring
  (``maxlen=capacity``); a long-lived controller can trace forever
  without growing. Open spans are not tracked globally — an abandoned
  span simply never reaches the ring.
- **never raises into the traced path.** Tracing is observability;
  every public entry point degrades to a no-op rather than break a
  reconcile.
"""

from __future__ import annotations

import collections
import contextvars
import datetime
import json
import uuid
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterator, List, Optional

from activemonitor_tpu.utils.clock import Clock

# the active span, task-local via contextvars; None outside any span
_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "activemonitor_span", default=None
)

DEFAULT_CAPACITY = 4096  # finished spans retained (ring)


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def current_span() -> Optional["Span"]:
    """The span the calling task is inside, or None."""
    return _CURRENT.get()


class detached:
    """``with detached():`` — run a block outside any span. Deferred
    callbacks (timer fires) execute under a context snapshot taken when
    they were ARMED; without detaching, a stale span from the arming
    cycle would adopt everything the callback does into a long-dead
    trace."""

    __slots__ = ("_token",)

    def __enter__(self) -> None:
        self._token = _CURRENT.set(None)

    def __exit__(self, *_exc) -> None:
        _CURRENT.reset(self._token)


def current_trace_id() -> str:
    """Trace id of the active span, or "" outside any span — what log
    lines and events stamp for correlation."""
    span = _CURRENT.get()
    return span.trace_id if span is not None else ""


@dataclass
class Span:
    """One timed phase of a trace. ``end``/``duration`` are stamped on
    exit; ``error`` records the exception type that escaped the span."""

    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float  # clock.monotonic() at entry
    start_ts: str  # clock.now() ISO form, for humans reading exports
    end: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)
    error: str = ""

    @property
    def duration(self) -> Optional[float]:
        if self.end is None:
            return None
        return max(0.0, self.end - self.start)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ts": self.start_ts,
            "duration_seconds": self.duration,
            "attrs": dict(self.attrs),
            "error": self.error,
        }


class _SpanScope:
    """Context manager returned by :meth:`Tracer.span` /
    :meth:`Tracer.trace`. Plain ``with`` works in async code too —
    contextvars set/reset inside one task compose with ``await``."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, _tb) -> None:
        if self._token is not None:
            _CURRENT.reset(self._token)
        if exc_type is not None and not self._span.error:
            self._span.error = exc_type.__name__
        self._tracer._finish(self._span)


class Tracer:
    """Creates spans and retains the finished ones in a bounded ring.

    One tracer per controller process (the reconciler owns it, the
    manager reaches it through ``reconciler.tracer`` — the same
    ownership shape as the clock and the metrics collector).
    """

    def __init__(
        self, clock: Optional[Clock] = None, capacity: int = DEFAULT_CAPACITY
    ):
        self.clock = clock or Clock()
        self._capacity = max(1, capacity)
        # NB: eviction is manual (no deque maxlen) so the per-trace
        # index below stays consistent with the ring
        self._finished: Deque[Span] = collections.deque()
        # trace_id -> that trace's retained spans, oldest first — the
        # O(trace) lookup behind spans_for_trace (goodput attribution
        # consults it on EVERY recorded run; an O(ring) scan there
        # would put 4096 comparisons on each status write)
        self._by_trace: Dict[str, List[Span]] = {}

    # -- span creation -------------------------------------------------
    def new_trace_id(self) -> str:
        """Pre-allocate a trace id for cross-task handoff (the manager
        mints one at enqueue time; the worker roots the cycle on it so
        queue wait and reconcile share a trace)."""
        return _new_trace_id()

    def span(self, name: str, **attrs: Any) -> _SpanScope:
        """Open a child span of whatever span the task is inside, or a
        fresh single-span trace outside any."""
        parent = _CURRENT.get()
        return self._scope(
            name,
            trace_id=parent.trace_id if parent else _new_trace_id(),
            parent_id=parent.span_id if parent else "",
            attrs=attrs,
        )

    def trace(
        self, name: str, trace_id: Optional[str] = None, **attrs: Any
    ) -> _SpanScope:
        """Open a ROOT span, deliberately ignoring any inherited
        context. The worker loop and timer-fired resubmissions need
        this: both run in tasks whose snapshot may still carry a
        previous cycle's span, and chaining cycles together would merge
        every run of a check into one unbounded trace."""
        return self._scope(
            name, trace_id=trace_id or _new_trace_id(), parent_id="", attrs=attrs
        )

    def _scope(
        self, name: str, trace_id: str, parent_id: str, attrs: Dict[str, Any]
    ) -> _SpanScope:
        span = Span(
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            name=name,
            start=self.clock.monotonic(),
            start_ts=self.clock.now().isoformat(),
            attrs=attrs,
        )
        return _SpanScope(self, span)

    def record_span(
        self,
        name: str,
        start: float,
        end: Optional[float] = None,
        trace_id: str = "",
        **attrs: Any,
    ) -> Span:
        """Record an already-elapsed phase (the queue-wait span: its
        start happened on another task, before any span existed). An
        explicit ``trace_id`` attaches the span to a trace the caller
        is NOT inside — the front door's admission span belongs to the
        cycle it triggered, but the decision runs on the request task,
        outside that cycle's context."""
        parent = _CURRENT.get()
        end_m = self.clock.monotonic() if end is None else end
        # start_ts must be the phase's START on the wall clock — project
        # the monotonic elapsed back from now, or a 30 s queue wait
        # would claim to begin at the instant it ended and the exported
        # timeline wouldn't line up
        elapsed = max(0.0, end_m - start)
        span = Span(
            trace_id=trace_id
            or (parent.trace_id if parent else _new_trace_id()),
            span_id=_new_span_id(),
            # a span grafted onto ANOTHER trace must not claim the
            # ambient span (of some unrelated trace) as its parent
            parent_id=(
                parent.span_id
                if parent and (not trace_id or parent.trace_id == trace_id)
                else ""
            ),
            name=name,
            start=start,
            start_ts=(
                self.clock.now() - datetime.timedelta(seconds=elapsed)
            ).isoformat(),
            end=end_m,
            attrs=attrs,
        )
        self._finish(span)
        return span

    def _finish(self, span: Span) -> None:
        if span.end is None:
            span.end = self.clock.monotonic()
        self._finished.append(span)
        self._by_trace.setdefault(span.trace_id, []).append(span)
        while len(self._finished) > self._capacity:
            evicted = self._finished.popleft()
            trace = self._by_trace.get(evicted.trace_id)
            if trace:
                # spans of one trace finish in ring order, so the
                # evicted one is the trace list's head
                trace.pop(0)
                if not trace:
                    del self._by_trace[evicted.trace_id]

    # -- export --------------------------------------------------------
    @property
    def finished_spans(self) -> List[Span]:
        return list(self._finished)

    def spans_for_trace(self, trace_id: str) -> List[Span]:
        """The finished spans of one trace, oldest first — the
        correlated-evidence lookup attribution and the flight recorder
        use (a cycle's dequeue span carries its queue wait). O(trace),
        not O(ring): served from the per-trace index."""
        if not trace_id:
            return []
        return list(self._by_trace.get(trace_id, ()))

    def traces(self) -> List[dict]:
        """Finished spans grouped per trace, oldest trace first — the
        `/debug/traces` payload and the JSONL export unit."""
        grouped: Dict[str, List[Span]] = {}
        order: List[str] = []
        for span in self._finished:
            if span.trace_id not in grouped:
                grouped[span.trace_id] = []
                order.append(span.trace_id)
            grouped[span.trace_id].append(span)
        out = []
        for trace_id in order:
            spans = grouped[trace_id]
            out.append(
                {
                    "trace_id": trace_id,
                    "span_count": len(spans),
                    "spans": [s.to_dict() for s in spans],
                }
            )
        return out

    # --trace-export rotation: the export appends (a long-lived
    # controller restarting into the same path keeps prior shutdowns'
    # traces) and rotates through the shared size cap first — the same
    # discipline the flight recorder applies to flightrec.jsonl
    DEFAULT_EXPORT_MAX_BYTES = 4 << 20
    DEFAULT_EXPORT_KEEP = 4

    def export_jsonl(
        self,
        path: str,
        max_bytes: int = DEFAULT_EXPORT_MAX_BYTES,
        keep: int = DEFAULT_EXPORT_KEEP,
    ) -> int:
        """Dump one JSON line per trace; returns how many were written.
        Size-capped: when the file at ``path`` already exceeds
        ``max_bytes`` it rotates aside (``<stem>-1 .. <stem>-keep``)
        before this export appends — an operator pointing
        ``--trace-export`` at one path forever gets a bounded set of
        files, never one unbounded JSONL. Best-effort by contract
        (shutdown path): an unwritable path logs nothing here — the
        caller decides how loud to be."""
        from activemonitor_tpu.obs.journal import rotate_capped

        rotate_capped(path, max_bytes, keep=keep)
        traces = self.traces()
        with open(path, "a") as f:
            for trace in traces:
                f.write(json.dumps(trace, default=str) + "\n")
        return len(traces)

    @staticmethod
    def read_jsonl(path: str) -> Iterator[dict]:
        """Parse an export back (tests, offline analysis)."""
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)
