"""Timed collectives — the measurement core of the ICI bandwidth probes.

The communication backend is XLA collectives over ICI/DCN
(`psum` / `all_gather` / `ppermute` under `shard_map` on a Mesh) — the
TPU-native equivalent of the NCCL/MPI backends the mandate describes;
the reference itself has none (SURVEY.md §5.8).

Measurement discipline (SURVEY.md §7 hard part (d)): time the
collective, not the compile and not the dispatch — each benchmark jits
a chain of k data-dependent collectives and takes the (2k−k) wall-clock
difference through a forced host readback, so compile, tunnel
roundtrips, and dispatch overhead cancel
(see utils/timing.chain_delta_seconds).

Bandwidth conventions follow NCCL-tests:

- *algbw* = payload bytes / time
- *busbw* = algbw × 2(n-1)/n for all-reduce (ring transfer volume),
  algbw × (n-1)/n for all-gather / reduce-scatter / all-to-all — the
  number comparable against rated link bandwidth.

This module times the XLA builtins; the explicit ppermute schedule
zoo (ring reduce-scatter+all-gather, recursive doubling, tree) lives
in parallel/schedules.py and reuses ``_bench`` so both report through
the same ``CollectiveResult``/busbw accounting.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from activemonitor_tpu.parallel.partition import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from activemonitor_tpu.utils.timing import chain_delta_seconds


@dataclass(frozen=True)
class CollectiveResult:
    name: str
    payload_bytes: int
    n_devices: int
    seconds_per_op: float
    algbw_gbps: float  # GB/s, payload/time
    busbw_gbps: float  # GB/s, NCCL busbw convention


def _payload(size_mb: float, dtype) -> tuple[int, int, int]:
    itemsize = jnp.dtype(dtype).itemsize
    elems = max(64, int(size_mb * 1e6 / itemsize))
    # payloads >= 8K elements keep the historical [rows, 1024] shape;
    # smaller ones narrow the row so the ~4KB latency-regime floor of
    # the sweep grid measures ~4KB, not a silently clamped 16KB (the
    # old max(8, ...) row floor under 1024 fixed cols)
    cols = 1024 if elems >= 8 * 1024 else max(8, elems // 8)
    rows = max(8, elems // cols)
    return rows, cols, rows * cols * itemsize


def _sharded_chain(mesh: Mesh, body, k: int, axis: str):
    """jit(shard_map(chain of k body applications)) ending in a scalar."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(None),
        check_vma=False,
    )
    def chain(x):
        for _ in range(k):
            x = body(x)
        # full-reduction readback: psum so every shard contributes
        return jax.lax.psum(x.astype(jnp.float32).sum(), axis)[None]

    return lambda x: chain(x)[0]


def _bench(
    name: str,
    mesh: Mesh,
    axis: str,
    size_mb: float,
    dtype,
    iters: int,
    make_body: Callable[[int, str], Callable],
    *,
    rows_multiple_of_n: bool = False,
    payload_mult: float = 1.0,
    busbw_factor: Callable[[int], float] = lambda n: 1.0,
) -> CollectiveResult:
    """Shared scaffold: payload shaping, the timed shard_map chain, and
    the NCCL accounting. ``make_body(n, axis)`` returns the per-round,
    shape-preserving collective body; ``payload_mult`` scales the
    per-shard bytes into the convention's reported payload (e.g. ×n for
    all-gather's total-data accounting)."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    rows, cols, shard_bytes = _payload(size_mb, dtype)
    if rows_multiple_of_n:
        # rows must divide by n so scattered shards keep a static shape
        rows = max(n, rows - rows % n)
        shard_bytes = rows * cols * jnp.dtype(dtype).itemsize
    body = make_body(n, axis)
    x = jnp.ones((rows * n, cols), dtype=dtype)
    seconds = chain_delta_seconds(
        lambda k: _sharded_chain(mesh, body, k, axis), x, k1=2, k2=6, iters=iters
    )
    payload = int(shard_bytes * payload_mult)
    algbw = payload / seconds / 1e9
    busbw = algbw * busbw_factor(n) if n > 1 else algbw
    return CollectiveResult(
        name=name,
        payload_bytes=payload,
        n_devices=n,
        seconds_per_op=seconds,
        algbw_gbps=algbw,
        busbw_gbps=busbw,
    )


def all_reduce_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained psum all-reduce over ``axis`` (default: the mesh's first
    axis — pass "dcn" on a multihost mesh to measure the cross-host
    direction; the other axes stay replicated)."""

    def make_body(n, ax):
        inv_n = jnp.asarray(1.0 / n, dtype)
        return lambda x: jax.lax.psum(x, ax) * inv_n  # mean keeps magnitude stable

    return _bench(
        "all_reduce", mesh, axis, size_mb, dtype, iters, make_body,
        busbw_factor=lambda n: 2 * (n - 1) / n,
    )


def all_gather_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained all-gather; each round gathers all shards then reduces
    back to shard shape (the reduce keeps rounds data-dependent — its
    local cost is included, so this slightly understates pure comm bw)."""

    def make_body(n, ax):
        inv_n = jnp.asarray(1.0 / n, dtype)

        def body(x):
            g = jax.lax.all_gather(x, ax)  # [n, rows, cols]
            return jnp.sum(g, axis=0) * inv_n

        return body

    # all-gather's NCCL accounting reports total gathered data (n×shard)
    n = mesh.shape[axis or mesh.axis_names[0]]
    return _bench(
        "all_gather", mesh, axis, size_mb, dtype, iters, make_body,
        payload_mult=float(n),
        busbw_factor=lambda n: (n - 1) / n,
    )


def reduce_scatter_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained psum-scatter; each round reduce-scatters the shard then
    tiles the result back to shard shape (a local copy that keeps rounds
    data-dependent and shape-stable — its HBM cost is included, so this
    slightly understates pure comm bw, mirroring all_gather above)."""

    def make_body(n, ax):
        inv_n = jnp.asarray(1.0 / n, dtype)

        def body(x):
            s = jax.lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True)
            return jnp.concatenate([s] * n, axis=0) * inv_n

        return body

    return _bench(
        "reduce_scatter", mesh, axis, size_mb, dtype, iters, make_body,
        rows_multiple_of_n=True,
        busbw_factor=lambda n: (n - 1) / n,
    )


def all_to_all_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained tiled all-to-all (the expert-parallel dispatch pattern,
    ops/moe.py) — shape-preserving, so the chain is pure communication;
    each round every device exchanges (n-1)/n of its shard."""

    def make_body(_n, ax):
        # (n, ax) is the body-factory contract _bench calls with;
        # all_to_all needs only the axis
        return lambda x: jax.lax.all_to_all(
            x, ax, split_axis=0, concat_axis=0, tiled=True
        )

    return _bench(
        "all_to_all", mesh, axis, size_mb, dtype, iters, make_body,
        rows_multiple_of_n=True,
        busbw_factor=lambda n: (n - 1) / n,
    )


def ppermute_ring_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained neighbor-shift over a ring — isolates single-hop ICI link
    speed (the building block of ring attention / pipelined collectives)."""

    def make_body(n, ax):
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lambda x: jax.lax.ppermute(x, ax, perm)

    return _bench("ppermute_ring", mesh, axis, size_mb, dtype, iters, make_body)


def ppermute_bidir_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    axis: str = "",
) -> CollectiveResult:
    """Chained BIDIRECTIONAL neighbor shift: the shard splits in halves
    permuted clockwise / counter-clockwise simultaneously — the wire
    pattern of bidirectional ring attention
    (ops/ring_attention.py variant="bidir"), driving both directions of
    every ring link per round. Same payload accounting as the
    unidirectional hop (full shard bytes per round), so on full-duplex
    ICI the achievable ceiling is 2x the unidirectional link bandwidth
    and the measured algbw approaching that ceiling is the evidence the
    second direction is real."""

    def make_body(n, ax):
        fwd = [(i, (i + 1) % n) for i in range(n)]
        bwd = [(i, (i - 1) % n) for i in range(n)]

        def body(x):
            half = x.shape[0] // 2
            a = jax.lax.ppermute(x[:half], ax, fwd)
            b = jax.lax.ppermute(x[half:], ax, bwd)
            return jnp.concatenate([a, b], axis=0)

        return body

    return _bench("ppermute_bidir", mesh, axis, size_mb, dtype, iters, make_body)
