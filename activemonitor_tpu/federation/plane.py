"""The federation plane: the manager-facing façade over the pieces.

One object the Manager wires (``--federation-config``) and the
FleetStatus reads: it owns the cluster registry, the capability
router, and (optionally) the global front door, drives the poll/sweep
cadence from the manager's goodput loop, and serves the ``/statusz``
``federation`` block plus the pinned ``healthcheck_federation_*``
gauges.

Config is a plain YAML/JSON document (see ``examples/federation/``)::

    liveness_seconds: 90
    clusters:
      - name: us-east1-v5p
        url: http://us-east1.monitor:8080
        device_kind: TPU v5p
        chips: 64
        topology: 4x4x4
        slices: [train-pod-a]
        dcn_gbps: 25

Transport stays OUT of this package: :attr:`FederationPlane.fetch` is
an async hook the manager wires to its aiohttp fetch (tests wire a
stub), so the whole plane runs under a FakeClock with no sockets.
"""

from __future__ import annotations

import logging
from typing import Awaitable, Callable, Optional

from activemonitor_tpu.federation.registry import (
    DEFAULT_LIVENESS_SECONDS,
    ClusterDescriptor,
    ClusterRegistry,
)
from activemonitor_tpu.federation.rollup import federate_statusz
from activemonitor_tpu.federation.routing import CapabilityRouter
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.federation")


class FederationPlane:
    """Registry + router + (optional) global door, as one wired unit."""

    def __init__(
        self,
        registry: ClusterRegistry,
        router: CapabilityRouter,
        door=None,  # GlobalFrontDoor (optional: registry-only planes)
    ):
        self.registry = registry
        self.router = router
        self.door = door
        # async url -> payload hook, wired by the Manager (aiohttp) or
        # a test stub; None disables polling (observe() fed directly)
        self.fetch: Optional[Callable[[str], Awaitable[Optional[dict]]]] = None

    @classmethod
    def from_config(
        cls,
        doc: dict,
        *,
        clock: Optional[Clock] = None,
        metrics=None,
        flightrec=None,
        door=None,
    ) -> "FederationPlane":
        """Build a plane from the ``--federation-config`` document:
        every entry under ``clusters`` becomes a descriptor (capability
        card derived from its ``device_kind`` via the rated tables) and
        joins the registry immediately."""
        doc = doc or {}
        registry = ClusterRegistry(
            clock=clock,
            liveness_seconds=float(
                doc.get("liveness_seconds") or DEFAULT_LIVENESS_SECONDS
            ),
            metrics=metrics,
            flightrec=flightrec,
        )
        for entry in doc.get("clusters") or []:
            registry.join(
                ClusterDescriptor.build(
                    str(entry.get("name") or ""),
                    url=str(entry.get("url") or ""),
                    device_kind=str(entry.get("device_kind") or ""),
                    chips=int(entry.get("chips") or 0),
                    topology=str(entry.get("topology") or ""),
                    slices=entry.get("slices") or (),
                    dcn_gbps=float(entry.get("dcn_gbps") or 0.0),
                )
            )
        router = CapabilityRouter(registry, metrics=metrics)
        return cls(registry, router, door=door)

    # -- the poll/sweep cadence (manager's goodput loop) -----------------
    async def poll(self) -> int:
        """One federation round: fetch every url'd cluster's /statusz
        into the registry (movement judges liveness), then sweep and
        refresh the gauges. A failed fetch is just absence of movement
        — the liveness window, not the error, decides health. Returns
        how many polls landed a payload."""
        landed = 0
        if self.fetch is not None:
            for descriptor in [
                self.registry.get(name) for name in self.registry.names()
            ]:
                if descriptor is None or not descriptor.url:
                    continue
                try:
                    payload = await self.fetch(descriptor.url)
                except Exception:
                    log.exception(
                        "federation poll failed for %s", descriptor.name
                    )
                    payload = None
                if isinstance(payload, dict):
                    self.registry.observe(descriptor.name, payload)
                    landed += 1
        self.sweep()
        return landed

    def sweep(self) -> None:
        """Liveness judgment + gauge refresh (also callable standalone
        for in-process clusters that feed ``registry.observe``
        directly)."""
        self.registry.sweep()
        self.registry.export_metrics()
        if self.registry.metrics is not None:
            try:
                ratio = self.federated()["fleet"]["goodput_ratio"]
                if ratio is not None:
                    self.registry.metrics.set_federation_goodput(ratio)
            except Exception:
                log.exception("federation goodput export failed")

    # -- reading ---------------------------------------------------------
    def federated(self) -> dict:
        """The federation-level rollup over every cluster's latest
        observed payload (two-level merge: each payload is already a
        replica payload or a per-cluster rollup)."""
        return federate_statusz(self.registry.payloads())

    def snapshot(self) -> dict:
        """The ``/statusz`` ``federation`` block: registry states plus
        the global door's ledger summary (None-door planes report
        door: null)."""
        snap = {
            "registry": self.registry.snapshot(),
            "door": self.door.snapshot() if self.door is not None else None,
        }
        return snap
