"""XLA compile smoke-test probe.

Detects the stuck-compile failure mode (SURVEY.md §5.3 TPU detectors):
jits the canonical probe transformer forward, wall-clocks cold compile
and measures warm execution, and fails if compile exceeds its deadline.
First TPU compiles legitimately take tens of seconds — the default
threshold reflects that; persistent-cache hits make subsequent runs
fast.

Timing discipline (utils/timing.py): the cold-compile number is wall
clock forced through a scalar host readback (a transfer cannot lie,
unlike ``block_until_ready`` on tunneled devices), and the warm
execution number uses the chain-delta method so dispatch/transport
overhead cancels.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    forward,
    init_params,
    tiny_config,
)
from activemonitor_tpu.probes.base import PhaseTimings, ProbeMetric, ProbeResult
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    compile_deadline_seconds: float = 120.0,
    batch: int = 4,
    seq: int = 128,
    tiny: bool = False,
) -> ProbeResult:
    timings = PhaseTimings()
    with timings.phase("init"):
        cfg = tiny_config() if tiny else ProbeModelConfig()
        seq = min(seq, cfg.max_seq_len)
        params = init_params(jax.random.key(0), cfg)
        tokens = jnp.zeros((batch, seq), jnp.int32)

    # cold compile: wall clock ending in a forced scalar readback
    scalar_fwd = jax.jit(lambda p, t: forward(p, t, cfg).mean())
    t0 = time.perf_counter()
    with timings.phase("compile"):
        float(scalar_fwd(params, tokens))
    compile_seconds = time.perf_counter() - t0

    # warm execution: chain-difference (constant overhead cancels). The
    # chain is a lax.scan — ONE traced body regardless of k, so the
    # chain compiles in ~constant time in a probe whose premise is that
    # compiles can be slow (an unrolled Python loop would compile k
    # copies of the forward).
    def make_chain(k: int):
        def chain(p, t):
            def step(carry, _):
                out = forward(p, carry, cfg)
                # REAL data dependence between iterations (argmax of the
                # logits feeds the next forward) — a foldable dependence
                # gets CSE'd by XLA and the delta collapses
                nxt = (jnp.argmax(out, axis=-1) % cfg.vocab_size).astype(jnp.int32)
                return nxt, out.mean()
            _, means = jax.lax.scan(step, t, None, length=k)
            return means[-1]
        return jax.jit(chain)

    with timings.phase("execute"):
        exec_seconds = chain_delta_seconds(make_chain, params, tokens)

    ok = compile_seconds <= compile_deadline_seconds
    return ProbeResult(
        ok=ok,
        summary=(
            f"compile {compile_seconds:.2f}s (deadline {compile_deadline_seconds:.0f}s), "
            f"exec {exec_seconds * 1e3:.2f}ms"
        ),
        metrics=[
            ProbeMetric(
                "xla-compile-seconds",
                compile_seconds,
                help="Cold jit compile wall-clock of the probe transformer forward",
            ),
            ProbeMetric(
                "xla-exec-milliseconds",
                exec_seconds * 1e3,
                help="Warm per-forward device time (chain-delta estimate)",
            ),
        ],
        details={
            "batch": batch,
            "seq": seq,
            "d_model": cfg.d_model,
            "n_layers": cfg.n_layers,
        },
        timings=timings,
    )
