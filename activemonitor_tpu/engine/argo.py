"""Argo workflow engine — real Workflow CRs via the Kubernetes API.

Capability-parity backend for cluster deployments
(reference: healthcheck_controller.go:502-534 create, :617 dynamic-client
poll), on the framework's own REST layer — the Argo controller is an
external process; this engine only creates Workflow objects and reads
``status.phase``, exactly the process boundary the reference keeps.

Divergence (improvement) from the reference's poll-only design: the
engine maintains a **watch-backed cache** per namespace (the informer
pattern controller-runtime uses for the HealthCheck objects themselves
but the reference never applies to Workflows). One WATCH stream per
namespace replaces O(checks × polls) GETs, and
:meth:`ArgoWorkflowEngine.wait_change` lets the reconciler's poll loop
wake the moment the Argo controller writes a terminal phase instead of
sleeping out its backoff delay — completion latency becomes
event-driven while the inverse-exp poll cadence remains as the upper
bound. The cache degrades transparently: a miss or an unhealthy watch
falls back to a direct GET, so a broken watch path can slow detection
but never change behavior.
"""

from __future__ import annotations

import asyncio
import copy
import logging
from typing import Callable, Dict, Optional

from activemonitor_tpu.engine.base import WF_INSTANCE_ID, WF_INSTANCE_ID_LABEL_KEY
from activemonitor_tpu.kube import ApiError, KubeApi, api_path

WF_GROUP = "argoproj.io"
WF_VERSION = "v1alpha1"
WF_PLURAL = "workflows"

# the cache only tracks THIS controller's workflows (the instance-id
# label every submitted spec carries) — a shared Argo namespace full of
# foreign workflows must not be mirrored into controller memory
WF_WATCH_SELECTOR = f"{WF_INSTANCE_ID_LABEL_KEY}={WF_INSTANCE_ID}"

log = logging.getLogger("activemonitor.engine")


class _NamespaceWatch:
    """One namespace's workflow watch: list-then-watch with reconnect
    and 410 re-list, feeding a local cache and a change condition."""

    def __init__(
        self,
        api: KubeApi,
        namespace: str,
        on_health: Optional[Callable[[str, bool], None]] = None,
        on_restart: Optional[Callable[[str], None]] = None,
    ):
        self._api = api
        self._namespace = namespace
        self._cache: Dict[str, dict] = {}
        self._healthy = False
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._on_health = on_health
        self._on_restart = on_restart
        self.changed = asyncio.Condition()

    @property
    def healthy(self) -> bool:
        return self._healthy

    def _emit_health(self, healthy: bool) -> None:
        if self._on_health is not None:
            try:
                self._on_health(self._namespace, healthy)
            except Exception:  # observability must never break the watch
                log.exception("watch health callback failed")

    def _set_healthy(self, healthy: bool) -> None:
        if healthy != self._healthy:
            self._emit_health(healthy)
        self._healthy = healthy

    def _emit_restart(self) -> None:
        """The stream is being re-established from scratch (410 re-list
        or an error retry) — counted so watch churn is a queryable rate,
        not a log-grep. Seamless end-of-stream reconnects from the last
        resourceVersion are NOT restarts; the cache stayed warm."""
        if self._on_restart is not None:
            try:
                self._on_restart(self._namespace)
            except Exception:  # observability must never break the watch
                log.exception("watch restart callback failed")

    def lookup(self, name: str) -> Optional[dict]:
        """Cached object, or None on a miss (caller falls back to GET —
        a miss can be a not-yet-observed create just as well as a
        deletion, so the cache never asserts absence)."""
        obj = self._cache.get(name)
        return copy.deepcopy(obj) if obj is not None else None

    def rv(self, name: str) -> Optional[str]:
        """resourceVersion without the deepcopy lookup() pays — change
        predicates compare this one string per notification."""
        obj = self._cache.get(name)
        if obj is None:
            return None
        return obj.get("metadata", {}).get("resourceVersion")

    def ensure_started(self) -> None:
        if self._stopped:
            return  # closed engines never resurrect their watches
        if self._task is None or self._task.done():
            if self._task is None:
                # seed the gauge so a watch that is unhealthy from its
                # very first connection attempt still has a 0 series —
                # the transition guard in _set_healthy would otherwise
                # never emit for a startup-degraded watch
                self._emit_health(self._healthy)
            else:
                # the task DIED (the retry loop never exits by design,
                # so something escaped it or cancelled it from outside):
                # whatever health state it left behind is stale, and the
                # stream is being re-established from scratch — surface
                # both before restarting
                self._set_healthy(False)
                self._emit_restart()
            self._task = asyncio.create_task(
                self._run(), name=f"wfwatch:{self._namespace}"
            )

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None and not self._task.done():
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                # Task.cancelling() is 3.11+; requires-python allows 3.10
                cancelling = getattr(asyncio.current_task(), "cancelling", None)
                if cancelling is not None:
                    if cancelling():
                        raise  # the CALLER is being cancelled — propagate
                elif not self._task.done() or not self._task.cancelled():
                    # 3.10 fallback: the child either has not finished
                    # (the CancelledError was delivered to US mid-await)
                    # or finished WITHOUT being cancelled — a completed,
                    # uncancelled child cannot be the origin of a
                    # CancelledError, so the caller is being cancelled
                    # and one-shot cancel delivery must propagate
                    raise
            except Exception:
                # the watch task died on its own error while we were
                # stopping it — already torn down, nothing to salvage
                log.debug("watch task error during stop", exc_info=True)

    async def _notify(self) -> None:
        async with self.changed:
            self.changed.notify_all()

    async def _run(self) -> None:
        try:
            await self._run_loop()
        finally:
            # the loop only exits via cancellation (stop()) or a bug
            # escaping the retry ladder; either way this task no longer
            # feeds the cache, so the watch must not keep advertising
            # its last health state — get() falls back to direct GETs
            # and the gauge reads 0 instead of lying
            self._set_healthy(False)
            try:
                await self._notify()  # wake wait_change off the dead watch
            except (asyncio.CancelledError, Exception):
                log.debug(
                    "watch teardown notify for %s skipped",
                    self._namespace,
                    exc_info=True,
                )

    async def _run_loop(self) -> None:
        path = api_path(WF_GROUP, WF_VERSION, WF_PLURAL, self._namespace)
        resource_version = ""
        while True:
            try:
                if not resource_version:
                    listing = await self._api.get(
                        path, params={"labelSelector": WF_WATCH_SELECTOR}
                    )
                    self._cache = {
                        o["metadata"]["name"]: o
                        for o in listing.get("items", [])
                    }
                    resource_version = listing.get("metadata", {}).get(
                        "resourceVersion", ""
                    )
                    self._set_healthy(True)
                    await self._notify()
                async for event in self._api.watch(
                    path,
                    resource_version=resource_version,
                    label_selector=WF_WATCH_SELECTOR,
                ):
                    obj = event.get("object", {}) or {}
                    rv = obj.get("metadata", {}).get("resourceVersion", "")
                    if rv:
                        resource_version = rv
                    etype = event.get("type")
                    if etype == "BOOKMARK":
                        continue
                    name = obj.get("metadata", {}).get("name", "")
                    if not name:
                        continue
                    if etype == "DELETED":
                        self._cache.pop(name, None)
                    else:
                        self._cache[name] = obj
                    await self._notify()
                # server closed the stream (timeout): reconnect from the
                # last seen resourceVersion, cache stays warm
            except asyncio.CancelledError:
                raise
            except ApiError as e:
                if e.status == 410:
                    # history expired: full re-list, cache rebuilt
                    self._emit_restart()
                    resource_version = ""
                    continue
                self._set_healthy(False)
                self._emit_restart()
                await self._notify()
                log.warning(
                    "workflow watch for %s degraded (%s); retrying in 1s",
                    self._namespace,
                    e,
                )
                await asyncio.sleep(1.0)
                resource_version = ""
            except Exception as e:
                self._set_healthy(False)
                self._emit_restart()
                await self._notify()
                log.warning(
                    "workflow watch for %s failed (%r); retrying in 1s",
                    self._namespace,
                    e,
                )
                await asyncio.sleep(1.0)
                resource_version = ""


class ArgoWorkflowEngine:
    name = "argo"  # engine label on submit/poll counters
    # submit/poll outcomes reach the shared circuit breaker through the
    # KubeApi transport (when wired there); the reconciler's engine
    # wrapper must not double-record them
    shares_kube_transport = True

    def __init__(
        self,
        api: Optional[KubeApi] = None,
        watch: bool = True,
        on_watch_health: Optional[Callable[[str, bool], None]] = None,
        on_watch_restart: Optional[Callable[[str], None]] = None,
    ):
        self._api = api if api is not None else KubeApi.from_default_config()
        self._watch_enabled = watch
        self._on_watch_health = on_watch_health
        self._on_watch_restart = on_watch_restart
        self._watches: Dict[str, _NamespaceWatch] = {}

    def _watch_for(self, namespace: str) -> Optional[_NamespaceWatch]:
        if not self._watch_enabled:
            return None
        watch = self._watches.get(namespace)
        if watch is None:
            watch = _NamespaceWatch(
                self._api,
                namespace,
                on_health=self._on_watch_health,
                on_restart=self._on_watch_restart,
            )
            self._watches[namespace] = watch
        watch.ensure_started()
        return watch

    async def submit(self, manifest: dict) -> str:
        namespace = manifest.get("metadata", {}).get("namespace", "default")
        created = await self._api.create(
            api_path(WF_GROUP, WF_VERSION, WF_PLURAL, namespace), manifest
        )
        # start the namespace watch alongside the first submission so it
        # is warm by the time the status loop starts reading
        self._watch_for(namespace)
        return created["metadata"]["name"]

    async def get(self, namespace: str, name: str) -> Optional[dict]:
        watch = self._watch_for(namespace)
        if watch is not None and watch.healthy:
            cached = watch.lookup(name)
            if cached is not None:
                return cached
            # miss: not-yet-observed create or a deletion — ask directly
        return await self.get_fresh(namespace, name)

    async def get_fresh(self, namespace: str, name: str) -> Optional[dict]:
        """Authoritative direct GET, bypassing the cache — the final
        poll after a timeout must judge the workflow on what the API
        server says NOW, not on a possibly-lagging cache (a Succeeded
        that landed during a watch reconnect gap must win)."""
        try:
            return await self._api.get(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, namespace, name)
            )
        except ApiError as e:
            if e.not_found:
                return None
            raise

    async def wait_change(self, namespace: str, name: str) -> None:
        """Block until the named workflow (or the watch's health)
        changes. No internal timeout: the caller races this against its
        own pacing sleep (the reconciler races it with clock.sleep so
        fake-clock tests keep driving time), cancelling the loser. With
        the watch disabled this never completes — the pacing sleep
        governs, preserving pure poll behavior."""
        watch = self._watch_for(namespace)
        if watch is None:
            await asyncio.Event().wait()  # pragma: no cover - never set
            return
        healthy0 = watch.healthy
        before_rv = watch.rv(name) if healthy0 else None

        def _changed() -> bool:
            if watch.healthy != healthy0:
                return True  # health flip: caller should re-poll directly
            if not watch.healthy:
                return False  # stay blocked while down; the sleep paces
            return watch.rv(name) != before_rv

        async with watch.changed:
            await watch.changed.wait_for(_changed)

    async def close(self) -> None:
        for watch in self._watches.values():
            await watch.stop()
