"""Inverse-exponential backoff with timeout.

Status polling starts slow and speeds up: the first wait is ``max_delay``
and each subsequent wait is multiplied by ``factor`` (<1), clamped at
``min_delay`` — a workflow is unlikely to finish immediately, so early
polls are wasted; late polls should be tight to minimize detection
latency. Mirrors keikoproj/inverse-exp-backoff as the reference uses it
(reference: healthcheck_controller.go:613,801).

Parameter derivation from a HealthCheck spec lives in
:func:`compute_backoff_params` (reference: healthcheck_controller.go:575-605).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional

from activemonitor_tpu.utils.clock import Clock

DEFAULT_FACTOR = 0.5


@dataclass(frozen=True)
class BackoffParams:
    max_delay: float  # seconds
    min_delay: float  # seconds
    factor: float
    timeout: float  # seconds; <=0 means no deadline


def compute_backoff_params(
    *,
    workflow_timeout: int,
    backoff_max: int = 0,
    backoff_min: int = 0,
    backoff_factor: str = "",
) -> BackoffParams:
    """Derive polling parameters from spec fields.

    Defaults: max = timeout/2, min = timeout/60, both clamped ≥ 1 s;
    factor 0.5 unless the spec's string field parses as a float
    (reference: healthcheck_controller.go:575-605 — unparseable factor
    logs and falls back, it does not error). Spec values ≤ 0 are treated
    as unset — a negative delay would otherwise become a hot poll loop.
    """
    if backoff_max <= 0:
        max_delay = float(workflow_timeout // 2)
        if max_delay <= 0:
            max_delay = 1.0
    else:
        max_delay = float(backoff_max)
    if backoff_min <= 0:
        min_delay = float(workflow_timeout // 60)
        if min_delay <= 0:
            min_delay = 1.0
    else:
        min_delay = float(backoff_min)

    factor = DEFAULT_FACTOR
    if backoff_factor:
        try:
            factor = float(backoff_factor)
        except ValueError:
            factor = DEFAULT_FACTOR
    return BackoffParams(
        max_delay=max_delay,
        min_delay=min_delay,
        factor=factor,
        timeout=float(workflow_timeout),
    )


class InverseExpBackoff:
    """Async poll pacer.

    Usage::

        ieb = InverseExpBackoff(params, clock)
        while True:
            poll()
            if not await ieb.next():
                # deadline exceeded — synthesize failure
                break

    ``next`` returns False immediately (without sleeping) once the
    deadline has passed, matching the reference loop shape where the
    body runs once more with a synthesized Failed status
    (reference: healthcheck_controller.go:627-632).

    ``jitter=True`` opts into FULL jitter (AWS-style): each returned
    delay is drawn uniformly from ``[0, delay]`` while the underlying
    schedule advances deterministically. Off by default — existing
    callers keep exact delays (fake-clock tests script them) — and
    turned on where synchronized sleepers would otherwise re-converge
    on the apiserver in one wave after an outage (the degraded-mode
    pacer in resilience/coordinator.py). ``rng`` injects a seeded
    ``random.Random`` for deterministic tests.
    """

    def __init__(
        self,
        params: BackoffParams,
        clock: Clock | None = None,
        *,
        jitter: bool = False,
        rng: Optional[random.Random] = None,
    ):
        self._params = params
        self._clock = clock or Clock()
        self._delay = params.max_delay
        self._jitter = jitter
        self._rng = rng
        self._deadline = (
            self._clock.monotonic() + params.timeout if params.timeout > 0 else None
        )

    @property
    def current_delay(self) -> float:
        return self._delay

    def expired(self) -> bool:
        return (
            self._deadline is not None
            and self._clock.monotonic() >= self._deadline
        )

    def advance(self) -> float:
        """Current delay, advancing the schedule — for callers that pace
        themselves (e.g. waiting on a watch event bounded by the delay)
        instead of sleeping here. With ``jitter`` on, the returned value
        is uniform in ``[0, delay]``; the schedule itself advances
        unjittered so the delay envelope stays deterministic."""
        delay = self._delay
        self._delay = max(self._delay * self._params.factor, self._params.min_delay)
        if self._jitter:
            uniform = self._rng.uniform if self._rng is not None else random.uniform
            return uniform(0.0, delay)
        return delay

    async def next(self) -> bool:
        if self.expired():
            return False
        await self._clock.sleep(self.advance())
        return True
